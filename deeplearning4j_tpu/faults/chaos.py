"""Deterministic, seed-driven fault injection for the training stack.

Every injector is reproducible: given the same seed and the same
training run, the same fault fires at the same place — which is what
makes the chaos suite a *regression* suite rather than a flake
generator. Faults on offer (the ones the recovery rail must survive):

- ``nan_gradients(sd, at_step)`` — device-side: the compiled train step
  replaces every gradient leaf with NaN at absolute iteration
  ``at_step`` (traced into the XLA program, so it works inside fused
  windows and scans). Arms via ``TrainingConfig`` and retraces; exiting
  the context disarms and retraces back to the clean program.
- ``poison_batches(it, at_step)`` — host-side one-shot: the batch
  feeding absolute step ``at_step`` has its features replaced with NaN.
  One-shot means a rolled-back retry passes cleanly — the
  self-healing end-to-end test's fault of choice.
- ``flaky_iterator(it, fail_at_batch)`` — the loader raises a transient
  ``IOError`` at a chosen batch index, a limited number of times.
- ``torn_shard(directory, shard_index)`` — datapipe IO fault: bit-flip
  or truncate a committed shard file on disk (restored on exit). With
  ``heal_after_failures=N`` the original bytes return after the reader
  has failed N verifications — transient bit-rot, the self-heal e2e's
  fault of choice; without it the damage is permanent and drives the
  shard-quarantine path.
- ``flaky_read(times, every)`` / ``slow_reader(delay_s)`` — patch the
  ONE shard-IO seam (``datapipe.reader._read_file_bytes``): transient
  ``IOError`` every Nth read / injected latency (straggler drills for
  the read-timeout backup path).
- ``worker_killer(at_batch)`` — a prefetch worker crashes while
  holding the claimed batch: drives the supervisor's exactly-once
  requeue + bounded-backoff respawn (and, at ``times=2``, the
  twice-lost typed failure).
- ``failing_os_replace(times)`` / ``failing_fsync(times)`` — the next
  ``times`` checkpoint commit renames / durability fsyncs raise
  ``OSError``, leaving exactly the torn ``step_N.tmp`` state a killed
  writer leaves.
- ``stalled_dispatch(delay_s, at_call)`` — a train dispatch blocks for
  ``delay_s`` before returning real results: the recoverable-stall
  drill for ``integrity.StallWatchdog`` (typed ``TrainingStalledError``
  + forensics + /healthz 503, then a clean rollback-retry).
- ``bitflip_param(at_call)`` — silent data corruption: one bit of the
  dispatched window's returned params flips, finite-in finite-out;
  ``refingerprint=True`` keeps the corruption self-consistent (SDC
  inside the dispatch — the replay probe's case), ``False`` leaves the
  device digest intact (a corrupted D2H copy — the capture check's
  case). With fingerprints off the flip is genuinely silent.
- ``rot_checkpoint(dir, step)`` — flip/truncate a committed checkpoint
  payload on disk without touching its manifest: the bit-rot
  ``restore_latest`` must skip and ``checkpoint.Scrubber``
  quarantines (``step_N.rotten``).
- ``sigterm_listener(at_iteration)`` — delivers SIGTERM to this process
  at a training iteration, mid-window (drives PreemptionHook drills).
- ``failing_exec(server, n, every)`` — serving-side: every ``every``-th
  ``ParallelInference`` exec raises a transient device error, ``n``
  times total (counter-deterministic; bisection retries count too) —
  drives the serving self-heal / circuit-breaker e2e tests.
- ``poison_request(template)`` — a NaN-rows request payload shaped like
  ``template``: the poisoned-batch-isolation e2e's fault of choice
  (XLA does not raise on NaN; the resilient dispatcher must detect the
  non-finite output rows and quarantine exactly this request).
- ``resource_exhausted(at_call)`` / ``oom_serving(server, at_call)`` —
  synthetic device OOM (a real ``XlaRuntimeError`` with the
  ``RESOURCE_EXHAUSTED:`` status) from the training dispatch / serving
  exec path: drives the OOM-forensics e2e — the exec paths must
  convert it to a structured ``memory.MemoryExhaustedError`` and the
  recovery rail must diagnose-and-abort, not retry
  (docs/observability.md "OOM forensics").
- ``host_loss(trainer, surviving_strategy, at_iteration)`` — elastic
  topology drill: the trainer's mesh shrinks mid-fit and a retryable
  ``host_loss`` fault fires; FaultTolerantFit resumes RESHARDED on the
  surviving devices (docs/elastic_training.md).
- ``host_killer(at_iteration)`` / ``FileBarrier`` — multi-process
  host-death drills: one process of a multihost dryrun ``os._exit``s
  mid-window (no cleanup, no barrier release); peers see a barrier
  timeout, the job dies, and the relaunched smaller job restores
  through ``checkpoint.reshard`` (ShardCountMismatchError).

Reference parity: optimize/listeners/FailureTestingListener.java
injected OOM/exit/exception at listener trigger points; this harness
additionally reaches INSIDE the compiled step (NaN grads), the data
pipeline, and the checkpoint commit protocol.
"""
from __future__ import annotations

import contextlib
import os
import signal as _signal
import threading
import time
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.training import Listener
from deeplearning4j_tpu.dataset.iterators import DataSetIterator
from deeplearning4j_tpu.faults.errors import TransientDeviceError


def _synthetic_resource_exhausted(nbytes: int) -> BaseException:
    """The backend's allocation-failure error, synthesized: a real
    ``XlaRuntimeError`` with the ``RESOURCE_EXHAUSTED:`` status (so the
    exec paths' detection — type AND message — exercises exactly the
    production code path), falling back to a same-named RuntimeError
    subclass where jaxlib's type is not constructible."""
    msg = (f"RESOURCE_EXHAUSTED: chaos: out of memory while trying to "
           f"allocate {int(nbytes)} bytes")
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        return XlaRuntimeError(msg)
    except Exception:       # pragma: no cover - jaxlib layout drift
        cls = type("XlaRuntimeError", (RuntimeError,), {})
        return cls(msg)


class ChaosSpec:
    """Device-side injection knobs read by the train-step tracer
    (``SameDiff._build_step_parts``). Attached as
    ``TrainingConfig._chaos_spec``; a None spec (the default) leaves the
    compiled program untouched."""

    def __init__(self, nan_grads_at: Optional[int] = None):
        self.nan_grads_at = nan_grads_at


class FlakyIterator(DataSetIterator):
    """Raises a transient loader error at batch ``fail_at_batch``
    (index within the pass), ``times`` times total across passes."""

    def __init__(self, wrapped: DataSetIterator, fail_at_batch: int,
                 times: int = 1, exc_factory=None, log: Optional[List] = None):
        self._wrapped = wrapped
        self.fail_at_batch = int(fail_at_batch)
        self.times_left = int(times)
        self._exc_factory = exc_factory or (
            lambda i: IOError(f"chaos: injected loader failure at "
                              f"batch {i}"))
        self._log = log if log is not None else []

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    def __iter__(self):
        for i, batch in enumerate(self._wrapped):
            if i == self.fail_at_batch and self.times_left > 0:
                self.times_left -= 1
                self._log.append({"event": "loader_exception",
                                  "batch_index": i, "t": time.time()})
                raise self._exc_factory(i)
            yield batch


class BatchPoisoner(DataSetIterator):
    """Replaces the batch at yield-count ``at_step`` with NaN features,
    ``times`` times total (default one-shot). The counter is batches
    yielded BY THIS WRAPPER across passes/epochs — equal to the absolute
    training iteration only while nothing upstream replays batches. An
    outer RetryingIterator's reset-and-fast-forward (or quarantine
    skips) re-consume earlier batches and shift the firing point
    relative to training iterations, so tests needing an EXACT step
    should assert on the sentinel's reported provenance (or use
    ``ChaosMonkey.nan_gradients``, which is iteration-exact by
    construction); ``at_step`` here chooses roughly-where, one-shot —
    which is all the self-heal drills need."""

    def __init__(self, wrapped: DataSetIterator, at_step: int,
                 times: int = 1, log: Optional[List] = None):
        self._wrapped = wrapped
        self.at_step = int(at_step)
        self.times_left = int(times)
        self._step = 0                  # absolute batches yielded ever
        self._log = log if log is not None else []

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    @staticmethod
    def _poison(part):
        if isinstance(part, (tuple, list)):
            return type(part)(BatchPoisoner._poison(p) for p in part)
        a = np.array(part, copy=True)
        if np.issubdtype(a.dtype, np.floating):
            a[...] = np.nan
        return a

    def __iter__(self):
        for batch in self._wrapped:
            if self._step == self.at_step and self.times_left > 0:
                self.times_left -= 1
                self._log.append({"event": "batch_poisoned",
                                  "step": self._step, "t": time.time()})
                if isinstance(batch, dict):
                    batch = {k: self._poison(v) for k, v in batch.items()}
                elif hasattr(batch, "features") and hasattr(batch, "labels"):
                    batch = (self._poison(batch.features), batch.labels)
                else:
                    f, l = batch
                    batch = (self._poison(f), l)
            self._step += 1
            yield batch


class TornShard:
    """Deterministic on-disk shard corruption (datapipe/): ``inject()``
    damages the committed shard file (``bitflip`` one payload byte, or
    ``truncate`` to half) while keeping the original bytes in memory;
    ``heal()`` restores them. As a context manager the shard is
    corrupted for the body and restored on exit.

    ``heal_after_failures=N`` makes the damage TRANSIENT: subscribed to
    a pipeline's event stream (``pipeline.subscribe(ts.observe)`` —
    done by ``ChaosMonkey.torn_shard(pipeline=...)``), the original
    bytes come back after the reader has failed N verification
    attempts on this shard — so the reader's retry budget heals the
    fault (flaky-NFS bit-rot), which is what the zero-dropped-samples
    self-heal e2e needs. Without it the corruption is permanent and
    the bounded budget quarantines the shard."""

    def __init__(self, directory: str, shard_index: int = 0,
                 mode: str = "bitflip",
                 heal_after_failures: Optional[int] = None,
                 log: Optional[List] = None):
        from deeplearning4j_tpu.datapipe.manifest import SHARD_FMT
        if mode not in ("bitflip", "truncate"):
            raise ValueError(f"mode {mode!r}: use 'bitflip'|'truncate'")
        self.shard_file = SHARD_FMT.format(i=int(shard_index))
        self.path = os.path.join(os.fspath(directory), self.shard_file)
        self.mode = mode
        self.heal_after = heal_after_failures
        self._log = log if log is not None else []
        with open(self.path, "rb") as fh:
            self._orig = fh.read()
        self._failures = 0
        self.healed = False

    def inject(self) -> "TornShard":
        if self.mode == "truncate":
            data = self._orig[: len(self._orig) // 2]
        else:
            buf = bytearray(self._orig)
            buf[len(buf) // 2] ^= 0xFF
            data = bytes(buf)
        with open(self.path, "wb") as fh:
            fh.write(data)
        self.healed = False
        self._log.append({"event": "shard_torn", "shard": self.shard_file,
                          "mode": self.mode, "t": time.time()})
        return self

    def heal(self) -> None:
        if self.healed:
            return
        with open(self.path, "wb") as fh:
            fh.write(self._orig)
        self.healed = True
        self._log.append({"event": "shard_healed",
                          "shard": self.shard_file, "t": time.time()})

    def observe(self, ev: dict) -> None:
        """Pipeline-event hook: count this shard's read failures and
        heal once ``heal_after_failures`` is reached (the restore runs
        on the worker thread, BETWEEN its retry attempts — so the next
        attempt reads good bytes)."""
        if self.healed or self.heal_after is None:
            return
        if ev.get("event") in ("read_retry", "shard_quarantined") and \
                ev.get("shard") == self.shard_file:
            self._failures += 1
            if self._failures >= self.heal_after:
                self.heal()

    def __enter__(self) -> "TornShard":
        return self.inject()

    def __exit__(self, *exc) -> None:
        self.heal()


class HostLossInjector(Listener):
    """Deterministic in-process host-loss drill: at training iteration
    ``at_iteration`` the trainer's world shrinks to
    ``surviving_strategy`` (the mesh a preemption would leave behind)
    and a structured :class:`TransientDeviceError` (cause
    ``"host_loss"``) aborts the fit — exactly what a lost slice looks
    like from the training loop. ``faults.FaultTolerantFit``'s rollback
    then restores the last committed checkpoint RESHARDED onto the
    surviving mesh (ParallelTrainer records ``last_reshard``) and the
    run continues on the shrunken topology.

    One-shot; the strategy swap persists (the host stays dead)."""

    frequency = 1

    def __init__(self, trainer, surviving_strategy, at_iteration: int,
                 log: Optional[List] = None):
        self.trainer = trainer
        self.surviving_strategy = surviving_strategy
        self.at_iteration = int(at_iteration)
        self.fired = False
        self._log = log if log is not None else []

    def iteration_done(self, sd, epoch, iteration, loss):
        if not self.fired and iteration >= self.at_iteration:
            self.fired = True
            lost = (self.trainer.strategy.mesh.n_devices
                    - self.surviving_strategy.mesh.n_devices)
            self._log.append({"event": "host_loss", "iteration": iteration,
                              "devices_lost": lost, "t": time.time()})
            self.trainer.strategy = self.surviving_strategy
            raise TransientDeviceError(
                f"chaos: injected host loss at iteration {iteration} "
                f"({lost} device(s) gone; surviving mesh "
                f"{dict(self.surviving_strategy.mesh.mesh.shape)})",
                step=int(iteration), epoch=int(epoch), cause="host_loss")


class HostKiller(Listener):
    """SIGKILL-grade host death for multi-process drills: at training
    iteration ``at_iteration`` the process exits immediately via
    ``os._exit`` — no atexit hooks, no final checkpoint, no barrier
    release; surviving peers discover the death as a barrier timeout.
    The piece :class:`SigtermListener` (graceful preemption) cannot
    simulate."""

    frequency = 1

    def __init__(self, at_iteration: int, exit_code: int = 137):
        self.at_iteration = int(at_iteration)
        self.exit_code = int(exit_code)

    def iteration_done(self, sd, epoch, iteration, loss):
        if iteration >= self.at_iteration:
            os._exit(self.exit_code)


class FileBarrier:
    """Cross-process barrier over a shared directory (marker files) —
    the CheckpointManager ``barrier=`` hook for multi-process chaos
    drills without ``jax.distributed``. Each arrival writes
    ``<run_id>.<tag>.g<generation>.<index>`` and spins until all
    ``count`` markers exist; a peer that dies mid-protocol surfaces as
    a ``TimeoutError`` here, which fails the save — the whole job dies,
    and the relaunched job recovers through the elastic restore path.

    Markers persist on disk, so a RELAUNCHED job reusing the same
    barrier directory must pass a fresh ``run_id`` (every peer of a
    launch the same one — e.g. an attempt counter from the launcher):
    otherwise the dead job's markers would satisfy the new job's waits
    instantly, letting a commit race an in-flight shard."""

    def __init__(self, directory: str, index: int, count: int,
                 timeout: float = 60.0, poll: float = 0.01,
                 run_id: str = "r0"):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.index = int(index)
        self.count = int(count)
        self.timeout = float(timeout)
        self.poll = float(poll)
        self.run_id = "".join(c if c.isalnum() or c in "._-" else "_"
                              for c in str(run_id))
        self._generations: dict = {}

    def __call__(self, tag: str) -> None:
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in str(tag))
        # a tag recurs when the same step is re-saved (rollback-retry);
        # stale markers from the earlier arrival would satisfy the wait
        # instantly and let a commit race an in-flight shard, so each
        # recurrence gets its own generation (peers agree because
        # multihost cadences are deterministic across processes)
        gen = self._generations.get(safe, 0)
        self._generations[safe] = gen + 1
        safe = f"{self.run_id}.{safe}.g{gen}"
        mine = os.path.join(self.directory, f"{safe}.{self.index}")
        with open(mine, "w", encoding="utf-8") as fh:
            fh.write("here\n")
        deadline = time.monotonic() + self.timeout
        want = [os.path.join(self.directory, f"{safe}.{i}")
                for i in range(self.count)]
        while True:
            if all(os.path.exists(p) for p in want):
                return
            if time.monotonic() > deadline:
                missing = [p for p in want if not os.path.exists(p)]
                raise TimeoutError(
                    f"chaos barrier {tag!r}: peer(s) never arrived "
                    f"within {self.timeout}s ({missing}) — a host is "
                    f"dead; the job should abort and relaunch elastic")
            time.sleep(self.poll)


class SigtermListener(Listener):
    """Delivers SIGTERM to this process at a chosen training iteration
    (one-shot) — mid-window under the fused tier, since flushes happen
    at window boundaries. Pair with checkpoint.PreemptionHook."""

    frequency = 1

    def __init__(self, at_iteration: int, log: Optional[List] = None):
        self.at_iteration = int(at_iteration)
        self.fired = False
        self._log = log if log is not None else []

    def iteration_done(self, sd, epoch, iteration, loss):
        if not self.fired and iteration >= self.at_iteration:
            self.fired = True
            self._log.append({"event": "sigterm", "iteration": iteration,
                              "t": time.time()})
            os.kill(os.getpid(), _signal.SIGTERM)


class MidStreamKiller:
    """Serving chaos: kill a fleet replica after it emits ``n`` more
    tokens — the mid-stream death the durable-request drill needs
    (``shutdown(drain=False)`` only fails QUEUED work; this aborts the
    in-flight generations too, typed ``ServerClosedError``, exactly
    what a SIGKILL looks like to clients holding handles).

    Deterministic: the count is over the server's own ``_emit`` calls,
    so the same trace kills at the same token every run. The emit hook
    runs ON the decode worker, which cannot join itself — so it trips
    the server's ``_killed`` flag (the worker aborts in-flight at its
    next step boundary) and finishes the kill (``replica.kill()`` →
    ``server.abort()``) from a side thread. ``fired.wait()`` to
    synchronize a drill on the kill having landed."""

    def __init__(self, replica, after_tokens: int,
                 log: Optional[List] = None):
        self.replica = replica
        self.after_tokens = int(after_tokens)
        self.fired = threading.Event()
        self._remaining = int(after_tokens)
        self._log = log if log is not None else []

    def arm(self) -> "MidStreamKiller":
        server = getattr(self.replica, "server", self.replica)
        orig = server._emit

        def emit(s, req, tok, _orig=orig, _server=server):
            _orig(s, req, tok)
            self._remaining -= 1
            if self._remaining == 0:
                self._log.append({"event": "kill_mid_stream",
                                  "replica": getattr(self.replica,
                                                     "name", "?"),
                                  "t": time.time()})
                _server._killed = True
                threading.Thread(target=self._finish,
                                 daemon=True).start()

        server._emit = emit
        return self

    def _finish(self) -> None:
        kill = getattr(self.replica, "kill", None)
        if kill is not None:
            kill()
        else:
            self.replica.abort()
        self.fired.set()


class ChaosMonkey:
    """Deterministic fault-injection front end. All randomness flows
    from the constructor seed; every injection is appended to ``log``.

    ::

        chaos = ChaosMonkey(seed=7)
        it = chaos.poison_batches(it, at_step=12)       # NaN at step 12
        it = chaos.flaky_iterator(it, fail_at_batch=3)  # loader IOError
        with chaos.failing_os_replace(times=1):
            mgr.save(step, state, blocking=True)        # torn commit
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)
        self.log: List[dict] = []

    def draw_step(self, lo: int, hi: int) -> int:
        """A seed-deterministic step/batch index in [lo, hi)."""
        return int(self.rng.integers(lo, hi))

    # -- data-pipeline faults -------------------------------------------
    def flaky_iterator(self, wrapped, fail_at_batch: Optional[int] = None,
                       n_batches: Optional[int] = None,
                       times: int = 1) -> FlakyIterator:
        if fail_at_batch is None:
            if n_batches is None:
                raise ValueError("pass fail_at_batch= or n_batches= to "
                                 "draw one from the seed")
            fail_at_batch = self.draw_step(0, n_batches)
        return FlakyIterator(wrapped, fail_at_batch, times=times,
                             log=self.log)

    def poison_batches(self, wrapped, at_step: Optional[int] = None,
                       n_steps: Optional[int] = None,
                       times: int = 1) -> BatchPoisoner:
        if at_step is None:
            if n_steps is None:
                raise ValueError("pass at_step= or n_steps= to draw one "
                                 "from the seed")
            at_step = self.draw_step(0, n_steps)
        return BatchPoisoner(wrapped, at_step, times=times, log=self.log)

    def torn_shard(self, directory, shard_index: Optional[int] = None,
                   n_shards: Optional[int] = None, mode: str = "bitflip",
                   heal_after_failures: Optional[int] = None,
                   pipeline=None) -> TornShard:
        """Corrupt a committed datapipe shard on disk (see
        :class:`TornShard`). ``pipeline=`` subscribes the healer to the
        pipeline's event stream so ``heal_after_failures`` counts real
        reader verdicts. Draws the shard from the seed when only
        ``n_shards`` is given. Use as a context manager (restores the
        bytes on exit) or call ``.inject()`` for permanent damage."""
        if shard_index is None:
            if n_shards is None:
                raise ValueError("pass shard_index= or n_shards= to draw "
                                 "one from the seed")
            shard_index = self.draw_step(0, n_shards)
        ts = TornShard(directory, shard_index, mode=mode,
                       heal_after_failures=heal_after_failures,
                       log=self.log)
        if pipeline is not None:
            pipeline.subscribe(ts.observe)
        return ts

    @contextlib.contextmanager
    def flaky_read(self, times: int = 1, every: int = 1,
                   match: Optional[str] = None) -> Iterator[dict]:
        """Transient IO at the shard-read seam: every ``every``-th
        ``datapipe.reader._read_file_bytes`` call (optionally filtered
        to paths containing ``match``) raises ``IOError``, ``times``
        times total — the reader's transient-retry budget must absorb
        it. Yields the mutable ``{"calls", "left"}`` state."""
        from deeplearning4j_tpu.datapipe import reader as _reader
        state = {"calls": 0, "left": int(times)}
        orig = _reader._read_file_bytes

        def chaotic_read(path):
            if match is None or match in os.path.basename(str(path)):
                state["calls"] += 1
                if state["left"] > 0 and state["calls"] % int(every) == 0:
                    state["left"] -= 1
                    self.log.append({"event": "read_failed",
                                     "path": str(path),
                                     "call": state["calls"],
                                     "t": time.time()})
                    raise IOError(f"chaos: injected read failure "
                                  f"({os.path.basename(str(path))})")
            return orig(path)

        _reader._read_file_bytes = chaotic_read
        try:
            yield state
        finally:
            _reader._read_file_bytes = orig

    @contextlib.contextmanager
    def slow_reader(self, delay_s: float, times: int = 1, every: int = 1,
                    match: Optional[str] = None) -> Iterator[dict]:
        """Latency injection at the shard-read seam: every ``every``-th
        read sleeps ``delay_s`` before returning real bytes, ``times``
        times total — the straggler drill for the prefetch pool's
        read-timeout backup requests."""
        from deeplearning4j_tpu.datapipe import reader as _reader
        state = {"calls": 0, "left": int(times)}
        orig = _reader._read_file_bytes

        def slow_read(path):
            if match is None or match in os.path.basename(str(path)):
                state["calls"] += 1
                if state["left"] > 0 and state["calls"] % int(every) == 0:
                    state["left"] -= 1
                    self.log.append({"event": "slow_read_injected",
                                     "path": str(path),
                                     "delay_s": float(delay_s),
                                     "t": time.time()})
                    time.sleep(float(delay_s))
            return orig(path)

        _reader._read_file_bytes = slow_read
        try:
            yield state
        finally:
            _reader._read_file_bytes = orig

    @contextlib.contextmanager
    def worker_killer(self, at_batch: int, times: int = 1
                      ) -> Iterator[dict]:
        """Kill the prefetch worker that claims plan batch ``at_batch``
        (an unstructured crash while HOLDING the claim), ``times``
        times total: the supervisor must requeue the batch exactly
        once and respawn the worker; at ``times=2`` the twice-lost
        batch fails typed instead of ping-ponging."""
        from deeplearning4j_tpu.datapipe import prefetch as _prefetch
        state = {"at_index": int(at_batch), "left": int(times),
                 "log": self.log}
        prev = _prefetch._CHAOS_KILL
        _prefetch._CHAOS_KILL = state
        try:
            yield state
        finally:
            _prefetch._CHAOS_KILL = prev

    # -- device faults --------------------------------------------------
    @contextlib.contextmanager
    def nan_gradients(self, sd, at_step: int) -> Iterator[None]:
        """Arm device-side NaN-gradient injection at absolute iteration
        ``at_step`` for the duration of the context. Retraces the train
        step on entry and exit (the injection is part of the compiled
        program)."""
        tc = sd.training_config
        if tc is None:
            raise ValueError("set sd.training_config first")
        prev = getattr(tc, "_chaos_spec", None)
        tc._chaos_spec = ChaosSpec(nan_grads_at=int(at_step))
        sd._mutated()
        self.log.append({"event": "nan_gradients_armed",
                         "step": int(at_step), "t": time.time()})
        try:
            yield
        finally:
            tc._chaos_spec = prev
            sd._mutated()

    @contextlib.contextmanager
    def transient_device_error(self, sd, at_call: int = 0) -> Iterator[None]:
        """Make the model's next fit attempt fail host-side with a
        :class:`TransientDeviceError` (simulates a lost device /
        preempted slice surfacing as a runtime error)."""
        raise_at = {"n": int(at_call)}
        orig = sd.fit

        def flaky_fit(*a, **kw):
            if raise_at["n"] == 0:
                raise_at["n"] = -1
                self.log.append({"event": "transient_device_error",
                                 "t": time.time()})
                raise TransientDeviceError(
                    "chaos: injected transient device loss",
                    cause="device")
            if raise_at["n"] > 0:
                raise_at["n"] -= 1
            return orig(*a, **kw)

        sd.fit = flaky_fit
        try:
            yield
        finally:
            sd.fit = orig

    @contextlib.contextmanager
    def bitflip_param(self, at_call: int = 1, times: int = 1,
                      bit: int = 17, leaf: Optional[str] = None,
                      refingerprint: bool = True) -> Iterator[dict]:
        """Silent data corruption: the ``at_call``-th train dispatch's
        RETURNED params have one bit flipped (``times`` times total) —
        finite-in, finite-out, so the isfinite sentinel never fires;
        only the integrity rail (integrity/fingerprint.py) can see it.

        Two flavors, matching the two real failure modes:

        - ``refingerprint=True`` (default) also recomputes the
          window's fingerprint output over the flipped state — the
          corruption is SELF-CONSISTENT, exactly what SDC inside the
          dispatch looks like (device state and its digest agree but
          differ from a correct replay). Detected by the REPLAY PROBE
          (``TrainingConfig.fingerprint_replay_every``) or a
          cross-replica check, NOT by the capture check.
        - ``refingerprint=False`` leaves the in-program digest intact —
          the corruption happened AFTER the device computed it (a bad
          device→host copy, host memory rot). Detected by the CAPTURE
          check at the next checkpoint.

        ``bit`` indexes into the flattened first float leaf (or
        ``leaf``, by name); with fingerprints off the flip is genuinely
        silent — the negative control the docs warn about. Yields the
        mutable ``{"calls", "left", "flips"}`` state."""
        from deeplearning4j_tpu.compilecache.aot import AOTDispatch
        state = {"calls": 0, "left": int(times), "flips": []}
        orig = AOTDispatch.__call__
        monkey = self

        def _flip_leaf(arr):
            import jax
            host = np.asarray(arr).copy()
            words = host.view(np.uint8).reshape(-1)
            pos = int(bit) % (words.size * 8)
            words[pos // 8] ^= np.uint8(1 << (pos % 8))
            return jax.device_put(host), pos

        def chaotic_call(disp, *args):
            out = orig(disp, *args)
            state["calls"] += 1
            if state["left"] <= 0 or state["calls"] < int(at_call) or \
                    not (isinstance(out, tuple) and out
                         and isinstance(out[0], dict)):
                return out
            state["left"] -= 1
            params = dict(out[0])
            name = leaf if leaf is not None else sorted(
                n for n, a in params.items()
                if np.issubdtype(np.asarray(a).dtype, np.floating))[0]
            params[name], pos = _flip_leaf(params[name])
            rest = list(out[1:])
            import jax
            fp_i = None
            if rest:
                last = rest[-1]
                if getattr(last, "dtype", None) is not None and \
                        getattr(last, "shape", None) == () and \
                        str(last.dtype) == "uint32":
                    fp_i = len(rest) - 1
            if refingerprint and fp_i is not None:
                # self-consistent SDC: re-digest the FLIPPED state
                # (params + svars + updater state — the same leaf set
                # the in-program digest covers)
                from deeplearning4j_tpu.integrity.fingerprint import \
                    np_fingerprint
                leaves = list(params.values()) \
                    + jax.tree_util.tree_leaves(rest[0]) \
                    + jax.tree_util.tree_leaves(rest[1])
                rest[fp_i] = jax.device_put(
                    np.uint32(np_fingerprint(leaves)))
            monkey.log.append({"event": "param_bit_flipped",
                               "call": state["calls"], "leaf": name,
                               "bit": pos,
                               "refingerprint": bool(refingerprint
                                                     and fp_i is not None),
                               "t": time.time()})
            state["flips"].append((name, pos))
            return (params, *rest)

        AOTDispatch.__call__ = chaotic_call
        try:
            yield state
        finally:
            AOTDispatch.__call__ = orig

    @contextlib.contextmanager
    def stalled_dispatch(self, delay_s: float, at_call: int = 1,
                         times: int = 1) -> Iterator[dict]:
        """Wedged-dispatch drill: the ``at_call``-th train dispatch
        blocks ``delay_s`` seconds before returning real results,
        ``times`` times total — a RECOVERABLE stall (the call
        eventually un-wedges). With a ``StallWatchdog`` armed past its
        deadline this drives the full stall path: forensics dump,
        ``{"type": "faults", "event": "stall"}``, /healthz 503, a typed
        ``TrainingStalledError`` at the boundary's exit, and a
        FaultTolerantFit rollback-retry that passes cleanly (one-shot).
        Yields the mutable ``{"calls", "left"}`` state."""
        from deeplearning4j_tpu.compilecache.aot import AOTDispatch
        state = {"calls": 0, "left": int(times)}
        orig = AOTDispatch.__call__
        monkey = self

        def chaotic_call(disp, *args):
            state["calls"] += 1
            if state["left"] > 0 and state["calls"] >= int(at_call):
                state["left"] -= 1
                monkey.log.append({"event": "dispatch_stalled",
                                   "call": state["calls"],
                                   "delay_s": float(delay_s),
                                   "t": time.time()})
                time.sleep(float(delay_s))
            return orig(disp, *args)

        AOTDispatch.__call__ = chaotic_call
        try:
            yield state
        finally:
            AOTDispatch.__call__ = orig

    # -- checkpoint/storage faults --------------------------------------
    def rot_checkpoint(self, directory, step: Optional[int] = None,
                       mode: str = "bitflip") -> dict:
        """Checkpoint bit-rot: damage the payload bytes of a COMMITTED
        step dir on disk (newest by default) without touching its
        manifest — the classic cold-storage rot ``restore_latest``'s
        verification must skip and the ``checkpoint.Scrubber``
        quarantines. ``mode='bitflip'`` flips one payload byte;
        ``'truncate'`` halves the largest payload file. Permanent (no
        heal — rot does not heal). Returns ``{step, file, mode}``."""
        from deeplearning4j_tpu.checkpoint.scrub import _STEP_RE
        directory = os.fspath(getattr(directory, "directory", directory))
        steps = sorted(int(m.group(1))
                       for m in (_STEP_RE.match(n)
                                 for n in os.listdir(directory)) if m)
        if not steps:
            raise ValueError(f"no committed steps under {directory!r}")
        step = steps[-1] if step is None else int(step)
        d = os.path.join(directory, f"step_{step:08d}")
        payloads = [n for n in sorted(os.listdir(d))
                    if n not in ("MANIFEST.json", "COMMIT")
                    and os.path.isfile(os.path.join(d, n))]
        target = max(payloads,
                     key=lambda n: os.path.getsize(os.path.join(d, n)))
        p = os.path.join(d, target)
        with open(p, "rb") as fh:
            data = fh.read()
        if mode == "truncate":
            data = data[: len(data) // 2]
        else:
            buf = bytearray(data)
            buf[len(buf) // 2] ^= 0xFF
            data = bytes(buf)
        with open(p, "wb") as fh:
            fh.write(data)
        self.log.append({"event": "checkpoint_rotted", "step": step,
                         "file": target, "mode": mode, "t": time.time()})
        return {"step": step, "file": target, "mode": mode}

    @contextlib.contextmanager
    def resource_exhausted(self, at_call: int = 1, times: int = 1,
                           nbytes: int = 1 << 30) -> Iterator[dict]:
        """Synthetic device OOM in the TRAINING exec path: the
        ``at_call``-th train dispatch (every ``AOTDispatch`` call —
        per-step steps, fused windows, scanned epochs — counts) raises
        ``RESOURCE_EXHAUSTED``, ``times`` times total. The fit tiers
        convert it into a structured
        :class:`~deeplearning4j_tpu.memory.MemoryExhaustedError` with
        forensics attached, and ``FaultTolerantFit`` publishes the
        ``{"type": "faults", "event": "oom"}`` diagnosis instead of
        burning its retry budget — the OOM-forensics e2e's fault of
        choice (docs/fault_tolerance.md). Yields the mutable
        ``{"calls", "left"}`` state."""
        from deeplearning4j_tpu.compilecache.aot import AOTDispatch
        state = {"calls": 0, "left": int(times)}
        orig = AOTDispatch.__call__

        def chaotic_call(disp, *args):
            state["calls"] += 1
            if state["left"] > 0 and state["calls"] >= int(at_call):
                state["left"] -= 1
                self.log.append({"event": "resource_exhausted",
                                 "call": state["calls"],
                                 "t": time.time()})
                raise _synthetic_resource_exhausted(nbytes)
            return orig(disp, *args)

        AOTDispatch.__call__ = chaotic_call
        try:
            yield state
        finally:
            AOTDispatch.__call__ = orig

    @contextlib.contextmanager
    def oom_serving(self, server, at_call: int = 1, times: int = 1,
                    nbytes: int = 1 << 30) -> Iterator[dict]:
        """Synthetic device OOM in the SERVING exec path: the
        ``at_call``-th graph execution under
        ``ParallelInference._execute`` raises ``RESOURCE_EXHAUSTED``
        from inside ``sd.output`` — so the server's own conversion
        (structured OOM + ``oom`` fault record + 503 /healthz) is what
        the test exercises, not a replaced ``_execute``."""
        state = {"calls": 0, "left": int(times)}
        sd = server._spec.sd
        orig = sd.output

        def chaotic_output(*args, **kw):
            state["calls"] += 1
            if state["left"] > 0 and state["calls"] >= int(at_call):
                state["left"] -= 1
                self.log.append({"event": "resource_exhausted",
                                 "call": state["calls"],
                                 "t": time.time()})
                raise _synthetic_resource_exhausted(nbytes)
            return orig(*args, **kw)

        sd.output = chaotic_output
        try:
            yield state
        finally:
            sd.output = orig

    # -- checkpoint/storage faults --------------------------------------
    @contextlib.contextmanager
    def failing_os_replace(self, times: int = 1,
                           match: str = "step_") -> Iterator[None]:
        """The next ``times`` ``os.replace`` calls whose source path
        contains ``match`` raise OSError — exactly the crash point the
        commit protocol's atomic publish must tolerate (everything is
        staged; the rename never lands)."""
        state = {"left": int(times)}
        orig = os.replace

        def chaotic_replace(src, dst, *a, **kw):
            if state["left"] > 0 and match in os.path.basename(str(src)):
                state["left"] -= 1
                self.log.append({"event": "os_replace_failed",
                                 "path": str(dst), "t": time.time()})
                raise OSError(f"chaos: injected os.replace failure "
                              f"publishing {dst}")
            return orig(src, dst, *a, **kw)

        os.replace = chaotic_replace
        try:
            yield
        finally:
            os.replace = orig

    @contextlib.contextmanager
    def failing_fsync(self, times: int = 1) -> Iterator[None]:
        """The next ``times`` ``os.fsync`` calls raise OSError (a dying
        disk / full quota during checkpoint staging)."""
        state = {"left": int(times)}
        orig = os.fsync

        def chaotic_fsync(fd):
            if state["left"] > 0:
                state["left"] -= 1
                self.log.append({"event": "fsync_failed", "t": time.time()})
                raise OSError("chaos: injected fsync failure")
            return orig(fd)

        os.fsync = chaotic_fsync
        try:
            yield
        finally:
            os.fsync = orig

    # -- serving faults -------------------------------------------------
    @contextlib.contextmanager
    def failing_exec(self, server, n: int = 1, every: int = 1,
                     exc_factory=None) -> Iterator[dict]:
        """Deterministic transient exec failures on a
        ``serving.ParallelInference``: every ``every``-th ``_execute``
        call raises (default :class:`TransientDeviceError`, cause
        ``"exec"``), ``n`` times total. The counter covers EVERY exec —
        including the bisection/probe retries the resilience rail
        issues — so a test can reason exactly about which dispatch
        fails. Yields the mutable ``{"calls", "left"}`` state."""
        state = {"calls": 0, "left": int(n)}
        factory = exc_factory or (lambda i: TransientDeviceError(
            f"chaos: injected exec failure (call {i})", cause="exec"))
        orig = server._execute

        def chaotic_execute(features, real_rows=None):
            state["calls"] += 1
            if state["left"] > 0 and state["calls"] % int(every) == 0:
                state["left"] -= 1
                self.log.append({"event": "exec_failed",
                                 "call": state["calls"], "t": time.time()})
                raise factory(state["calls"])
            return orig(features, real_rows=real_rows)

        server._execute = chaotic_execute
        try:
            yield state
        finally:
            server._execute = orig

    def poison_request(self, template) -> np.ndarray:
        """A request payload shaped like ``template`` with every
        floating value replaced by NaN — the poisoned request the
        bisecting dispatcher must quarantine while its co-batched
        neighbours still serve bit-identically."""
        a = np.array(template, copy=True)
        if np.issubdtype(a.dtype, np.floating):
            a[...] = np.nan
        self.log.append({"event": "request_poisoned",
                         "shape": list(a.shape), "t": time.time()})
        return a

    # -- process faults -------------------------------------------------
    def sigterm_listener(self, at_iteration: int) -> SigtermListener:
        return SigtermListener(at_iteration, log=self.log)

    # -- topology faults ------------------------------------------------
    def host_loss(self, trainer, surviving_strategy,
                  at_iteration: Optional[int] = None,
                  n_steps: Optional[int] = None) -> HostLossInjector:
        """In-process host-loss drill (see :class:`HostLossInjector`):
        mid-fit, the trainer's mesh shrinks to ``surviving_strategy``
        and a retryable ``host_loss`` fault fires — the elastic e2e's
        fault of choice. Draws the iteration from the seed when only
        ``n_steps`` is given."""
        if at_iteration is None:
            if n_steps is None:
                raise ValueError("pass at_iteration= or n_steps= to draw "
                                 "one from the seed")
            at_iteration = self.draw_step(1, n_steps)
        return HostLossInjector(trainer, surviving_strategy, at_iteration,
                                log=self.log)

    def host_killer(self, at_iteration: int, exit_code: int = 137
                    ) -> HostKiller:
        """SIGKILL-grade process death at an iteration (multi-process
        dryrun drills; see :class:`HostKiller`)."""
        return HostKiller(at_iteration, exit_code=exit_code)

    def kill_mid_stream(self, replica, after_tokens: int
                        ) -> MidStreamKiller:
        """Kill a serving replica after ``after_tokens`` more emitted
        tokens — in-flight generations fail typed mid-stream (the
        fleet durability drill; see :class:`MidStreamKiller`). Armed
        immediately."""
        return MidStreamKiller(replica, after_tokens,
                               log=self.log).arm()
