"""ONNX ModelProto schema views + builder over the protowire codec.

Reference parity: nd4j samediff-import-onnx (Kotlin rule registry over
generated onnx protobuf bindings; ImportGraph.kt:218). Field numbers are
the frozen public onnx.proto3 schema — schema constants, not code:

ModelProto:    ir_version=1, opset_import=8, graph=7
GraphProto:    node=1, name=2, initializer=5, input=11, output=12
NodeProto:     input=1, output=2, name=3, op_type=4, attribute=5
AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, strings=9,
               type=20 (FLOAT=1, INT=2, STRING=3, TENSOR=4, FLOATS=6,
               INTS=7, STRINGS=8)
TensorProto:   dims=1, data_type=2, float_data=4, int32_data=5,
               string_data=6, int64_data=7, name=8, raw_data=9,
               double_data=10, uint64_data=11
ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1 →
               {elem_type=1, shape=2 → dim=1 → {dim_value=1, dim_param=2}}
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.modelimport.protowire import Fields
from deeplearning4j_tpu.modelimport.tf_builder import (
    field_bytes, field_string, field_varint)

# onnx TensorProto.DataType enum
ONNX_DTYPES: Dict[int, Optional[np.dtype]] = {
    1: np.dtype(np.float32), 2: np.dtype(np.uint8), 3: np.dtype(np.int8),
    4: np.dtype(np.uint16), 5: np.dtype(np.int16), 6: np.dtype(np.int32),
    7: np.dtype(np.int64), 9: np.dtype(np.bool_), 10: np.dtype(np.float16),
    11: np.dtype(np.float64), 12: np.dtype(np.uint32),
    13: np.dtype(np.uint64),
}
NP_TO_ONNX = {v: k for k, v in ONNX_DTYPES.items() if v is not None}


def onnx_dtype_to_np(enum: int) -> np.dtype:
    if enum == 16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    dt = ONNX_DTYPES.get(enum)
    if dt is None:
        raise ValueError(f"unsupported ONNX dtype enum {enum}")
    return dt


def decode_tensor(t: Fields) -> np.ndarray:
    dims = t.repeated_varint(1)
    enum = t.varint(2)
    np_dtype = onnx_dtype_to_np(enum)
    raw = t.bytes_(9)
    if raw:
        return np.frombuffer(raw, np_dtype).copy().reshape(dims)
    if enum == 1:
        vals = np.asarray(t.repeated_f32(4), np.float32)
    elif enum == 11:
        vals = np.asarray(t.repeated_f64(10), np.float64)
    elif enum in (6, 2, 3, 4, 5, 9):
        vals = np.asarray(t.repeated_svarint(5), np_dtype)
    elif enum == 7:
        vals = np.asarray(t.repeated_svarint(7), np.int64)
    elif enum in (12, 13):
        vals = np.asarray(t.repeated_varint(11), np_dtype)
    else:
        raise ValueError(f"cannot decode ONNX tensor dtype {enum}")
    return vals.reshape(dims)


class Attribute:
    FLOAT, INT, STRING, TENSOR = 1, 2, 3, 4
    FLOATS, INTS, STRINGS = 6, 7, 8

    def __init__(self, fields: Fields):
        self._f = fields
        self.name = fields.string(1)
        self.type = fields.varint(20)

    @property
    def f(self) -> float:
        return self._f.f32(2)

    @property
    def i(self) -> int:
        return self._f.svarint(3)

    @property
    def s(self) -> str:
        return self._f.bytes_(4).decode("utf-8", "replace")

    @property
    def t(self) -> np.ndarray:
        m = self._f.message(5)
        if m is None:
            raise ValueError(f"attribute {self.name!r} has no tensor")
        return decode_tensor(m)

    @property
    def floats(self) -> List[float]:
        return self._f.repeated_f32(7)

    @property
    def ints(self) -> List[int]:
        return self._f.repeated_svarint(8)

    @property
    def strings(self) -> List[str]:
        return [b.decode("utf-8", "replace")
                for b in self._f.repeated_bytes(9)]


class NodeProto:
    def __init__(self, fields: Fields):
        self.inputs = fields.repeated_string(1)
        self.outputs = fields.repeated_string(2)
        self.name = fields.string(3)
        self.op_type = fields.string(4)
        self.attrs: Dict[str, Attribute] = {}
        for af in fields.repeated_message(5):
            a = Attribute(af)
            self.attrs[a.name] = a

    def attr(self, name: str) -> Optional[Attribute]:
        return self.attrs.get(name)

    def __repr__(self):
        return (f"NodeProto({self.op_type} {self.name!r} "
                f"{self.inputs}->{self.outputs})")


def _decode_value_info(f: Fields):
    """ValueInfoProto -> (name, dtype enum, [dims] with -1 for symbolic)."""
    name = f.string(1)
    tp = f.message(2)
    elem, dims = 0, None
    if tp is not None:
        tt = tp.message(1)
        if tt is not None:
            elem = tt.varint(1)
            shp = tt.message(2)
            if shp is not None:
                dims = []
                for d in shp.repeated_message(1):
                    dims.append(d.svarint(1) if d.has(1) else -1)
    return name, elem, dims


class OnnxGraph:
    def __init__(self, fields: Fields):
        self.nodes: List[NodeProto] = [NodeProto(f)
                                       for f in fields.repeated_message(1)]
        self.name = fields.string(2)
        self.initializers: Dict[str, np.ndarray] = {}
        for tf_ in fields.repeated_message(5):
            arr = decode_tensor(tf_)
            self.initializers[tf_.string(8)] = arr
        self.inputs = [_decode_value_info(f)
                       for f in fields.repeated_message(11)]
        self.outputs = [_decode_value_info(f)
                        for f in fields.repeated_message(12)]


class OnnxModel:
    def __init__(self, data: bytes):
        fields = Fields(data)
        g = fields.message(7)
        if g is None:
            raise ValueError("not an ONNX ModelProto (no graph field)")
        self.graph = OnnxGraph(g)

    @staticmethod
    def from_file(path: str) -> "OnnxModel":
        with open(path, "rb") as fh:
            return OnnxModel(fh.read())


# ---------------------------------------------------------------------------
# builder (fixture generation without an onnx install; same role as
# tf_builder for TF graphs)
def tensor_proto(arr: np.ndarray, name: str = "") -> bytes:
    arr = np.asarray(arr, order="C")
    out = b""
    for d in arr.shape:
        out += field_varint(1, d)
    out += field_varint(2, NP_TO_ONNX[arr.dtype])
    if name:
        out += field_string(8, name)
    out += field_bytes(9, arr.tobytes())
    return out


def attribute(name: str, value) -> bytes:
    import struct
    out = field_string(1, name)
    if isinstance(value, float):
        out += field_varint(20, Attribute.FLOAT)
        out += b"\x15" + struct.pack("<f", value)     # field 2, fixed32
    elif isinstance(value, int):
        out += field_varint(3, value) + field_varint(20, Attribute.INT)
    elif isinstance(value, str):
        out += field_string(4, value) + field_varint(20, Attribute.STRING)
    elif isinstance(value, np.ndarray):
        out += field_bytes(5, tensor_proto(value))
        out += field_varint(20, Attribute.TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                out += b"\x3d" + struct.pack("<f", v)  # field 7, fixed32
            out += field_varint(20, Attribute.FLOATS)
        else:
            for v in value:
                out += field_varint(8, int(v))
            out += field_varint(20, Attribute.INTS)
    else:
        raise TypeError(f"unsupported attribute {type(value)}")
    return out


def node_proto(op_type: str, inputs, outputs, name: str = "",
               **attrs) -> bytes:
    out = b""
    for i in inputs:
        out += field_string(1, i)
    for o in outputs:
        out += field_string(2, o)
    out += field_string(3, name or outputs[0])
    out += field_string(4, op_type)
    for k, v in attrs.items():
        out += field_bytes(5, attribute(k, v))
    return out


def value_info(name: str, dtype_enum: int, dims) -> bytes:
    dim_bytes = b""
    for d in dims:
        dim_bytes += field_bytes(1, field_varint(1, d) if d >= 0 else b"")
    tt = field_varint(1, dtype_enum) + field_bytes(2, dim_bytes)
    tp = field_bytes(1, tt)
    return field_string(1, name) + field_bytes(2, tp)


class OnnxModelBuilder:
    """Builds serialized ModelProto bytes (test fixtures / export)."""

    def __init__(self):
        self._nodes: List[bytes] = []
        self._inits: List[bytes] = []
        self._inputs: List[bytes] = []
        self._outputs: List[bytes] = []

    def node(self, op_type: str, inputs, outputs, name: str = "", **attrs):
        self._nodes.append(node_proto(op_type, inputs, outputs, name,
                                      **attrs))
        return self

    def initializer(self, name: str, arr) -> "OnnxModelBuilder":
        self._inits.append(tensor_proto(np.asarray(arr), name))
        return self

    def input(self, name: str, dims, dtype=np.float32):
        self._inputs.append(value_info(name, NP_TO_ONNX[np.dtype(dtype)],
                                       dims))
        return self

    def output(self, name: str, dims=(), dtype=np.float32):
        self._outputs.append(value_info(name, NP_TO_ONNX[np.dtype(dtype)],
                                        dims))
        return self

    def build(self) -> bytes:
        g = b""
        for n in self._nodes:
            g += field_bytes(1, n)
        g += field_string(2, "graph")
        for i in self._inits:
            g += field_bytes(5, i)
        for i in self._inputs:
            g += field_bytes(11, i)
        for o in self._outputs:
            g += field_bytes(12, o)
        out = field_varint(1, 8)                    # ir_version
        out += field_bytes(7, g)
        return out
