"""TensorFlow GraphDef *builder*: protobuf wire encoder + NodeDef helpers.

Reference parity: the reference consumes frozen GraphDefs produced by TF
itself (samediff-import-tensorflow test resources are .pb files exported
from TF). This environment has no TensorFlow, so the framework ships the
inverse of modelimport/protowire.py — a minimal wire-format ENCODER — plus
GraphDef/NodeDef/TensorProto builders. Uses:

- test fixtures: golden TF graphs are constructed programmatically and fed
  to the importer (tests/test_tf_import.py), the same methodology as the
  hand-written Keras h5 fixtures;
- model construction: zoo/bert builds a faithful frozen-BERT GraphDef via
  these builders (BASELINE config 4's input artifact);
- export: a SameDiff graph restricted to TF-mappable ops can be serialized
  for TF-side consumption.

Field numbers are the frozen public schema of
tensorflow/core/framework/{graph,node_def,attr_value,tensor,tensor_shape,
types}.proto — the same constants documented in tf_pb.py.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

# numpy dtype -> TF DataType enum (inverse of tf_pb.TF_DTYPES)
NP_TO_TF_DTYPE = {
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.int16): 5,
    np.dtype(np.int8): 6,
    np.dtype(np.int64): 9,
    np.dtype(np.bool_): 10,
    np.dtype(np.uint16): 17,
    np.dtype(np.float16): 19,
    np.dtype(np.uint32): 22,
    np.dtype(np.uint64): 23,
}


def np_to_tf_dtype(dt) -> int:
    dt = np.dtype(dt)
    if dt.name == "bfloat16":
        return 14
    try:
        return NP_TO_TF_DTYPE[dt]
    except KeyError:
        raise ValueError(f"no TF dtype for numpy dtype {dt}") from None


# ---------------------------------------------------------------------------
# wire primitives
def _varint(value: int) -> bytes:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement int64, per proto encoding
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def field_bytes(field: int, data: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(data)) + data


def field_string(field: int, s: str) -> bytes:
    return field_bytes(field, s.encode("utf-8"))


def field_f32(field: int, value: float) -> bytes:
    return _varint(field << 3 | 5) + struct.pack("<f", value)


# ---------------------------------------------------------------------------
# schema builders
def tensor_shape_proto(dims: Optional[Sequence[int]]) -> bytes:
    """TensorShapeProto: dim=2{size=1}, unknown_rank=3."""
    if dims is None:
        return field_varint(3, 1)
    out = b""
    for d in dims:
        out += field_bytes(2, field_varint(1, int(d)))
    return out


def tensor_proto(arr: np.ndarray) -> bytes:
    """TensorProto with tensor_content encoding (dtype=1, shape=2, content=4)."""
    # NOT ascontiguousarray — it promotes 0-d arrays to 1-d
    arr = np.asarray(arr, order="C")
    enum = np_to_tf_dtype(arr.dtype)
    out = field_varint(1, enum)
    out += field_bytes(2, tensor_shape_proto(arr.shape))
    out += field_bytes(4, arr.tobytes())
    return out


def attr_value(value) -> bytes:
    """Encode one AttrValue from a python value (type-directed):
    bytes/str->s, bool->b, int->i, float->f, np.ndarray->tensor,
    ("dtype", enum)->type, ("shape", dims)->shape, list[int]->list.i,
    list[str]->list.s, list[float]->list.f.
    """
    if isinstance(value, tuple) and len(value) == 2 and value[0] == "dtype":
        return field_varint(6, int(value[1]))
    if isinstance(value, tuple) and len(value) == 2 and value[0] == "func":
        # NameAttrList (field 10): name=1 — While/If branch references
        return field_bytes(10, field_string(1, value[1]))
    if isinstance(value, tuple) and len(value) == 2 and value[0] == "shape":
        return field_bytes(7, tensor_shape_proto(value[1]))
    if isinstance(value, bool):
        return field_varint(5, int(value))
    if isinstance(value, (bytes,)):
        return field_bytes(2, value)
    if isinstance(value, str):
        return field_string(2, value)
    if isinstance(value, int):
        return field_varint(3, value)
    if isinstance(value, float):
        return field_f32(4, value)
    if isinstance(value, np.ndarray):
        return field_bytes(8, tensor_proto(value))
    if isinstance(value, (list, tuple)):
        lv = b""
        for v in value:
            if isinstance(v, bool):
                lv += field_varint(5, int(v))
            elif isinstance(v, int):
                lv += field_varint(3, v)
            elif isinstance(v, float):
                lv += field_f32(4, v)
            elif isinstance(v, str):
                lv += field_string(2, v)
            else:
                raise TypeError(f"unsupported attr list element {type(v)}")
        return field_bytes(1, lv)
    raise TypeError(f"unsupported attr value {type(value)}")


def node_def(name: str, op: str, inputs: Sequence[str] = (),
             attrs: Optional[Dict[str, object]] = None) -> bytes:
    """NodeDef: name=1, op=2, input=3, attr=5 (map entry key=1, value=2)."""
    out = field_string(1, name) + field_string(2, op)
    for i in inputs:
        out += field_string(3, i)
    for k, v in (attrs or {}).items():
        entry = field_string(1, k) + field_bytes(2, attr_value(v))
        out += field_bytes(5, entry)
    return out


def function_def(name: str, args: Sequence, outputs: Sequence,
                 body: "GraphDefBuilder") -> bytes:
    """Encode a FunctionDef (the subgraph a TF2 functional While/If node
    invokes). ``args``: [(arg_name, np_dtype)]; ``outputs``:
    [(output_name, body_ref, np_dtype)] where body_ref is the function-
    internal tensor ref (e.g. "mul:z:0"); ``body``: a GraphDefBuilder
    holding the body NodeDefs (inputs reference arg names / node refs).

    Wire: FunctionDef signature=1 (OpDef name=1, input_arg=2,
    output_arg=3; ArgDef name=1 type=3), node_def=3, ret=4 (map)."""
    sig = field_string(1, name)
    for an, dt in args:
        sig += field_bytes(2, field_string(1, an)
                           + field_varint(3, np_to_tf_dtype(dt)))
    for on, _ref, dt in outputs:
        sig += field_bytes(3, field_string(1, on)
                           + field_varint(3, np_to_tf_dtype(dt)))
    out = field_bytes(1, sig)
    for nd in body._nodes:
        out += field_bytes(3, nd)
    for on, ref, _dt in outputs:
        out += field_bytes(4, field_string(1, on) + field_string(2, ref))
    return out


class GraphDefBuilder:
    """Accumulates NodeDefs and serializes a frozen-graph .pb byte string."""

    def __init__(self):
        self._nodes: List[bytes] = []
        self._functions: List[bytes] = []

    def add_function(self, fbytes: bytes) -> None:
        """Attach an encoded FunctionDef to the graph's library."""
        self._functions.append(fbytes)

    def raw_node(self, name: str, op: str, inputs: Sequence[str] = (),
                 attrs: Optional[Dict[str, object]] = None) -> str:
        self._nodes.append(node_def(name, op, inputs, attrs))
        return name

    def const(self, name: str, value) -> str:
        arr = np.asarray(value)
        return self.raw_node(name, "Const", (), {
            "dtype": ("dtype", np_to_tf_dtype(arr.dtype)),
            "value": arr,
        })

    def placeholder(self, name: str, shape: Optional[Sequence[int]] = None,
                    dtype=np.float32) -> str:
        return self.raw_node(name, "Placeholder", (), {
            "dtype": ("dtype", np_to_tf_dtype(dtype)),
            "shape": ("shape", shape),
        })

    def node(self, op: str, name: str, *inputs: str, **attrs) -> str:
        """Generic op node; attrs passed python-typed (see attr_value)."""
        return self.raw_node(name, op, inputs, attrs or None)

    def build(self) -> bytes:
        """GraphDef: node=1 repeated, library=2 (function=1 repeated)."""
        out = b"".join(field_bytes(1, n) for n in self._nodes)
        if self._functions:
            lib = b"".join(field_bytes(1, f) for f in self._functions)
            out += field_bytes(2, lib)
        return out

    def save(self, path: str) -> None:
        with open(path, "wb") as fh:
            fh.write(self.build())
