"""Model import (reference: deeplearning4j-modelimport + samediff-import).

Keras .h5 → layer-API networks; frozen TF GraphDef .pb → SameDiff graphs.
"""
from deeplearning4j_tpu.modelimport.keras_import import (
    KerasModelImport, import_keras_model_and_weights,
    import_keras_sequential_model_and_weights)
from deeplearning4j_tpu.modelimport.tf_import import (
    TFImportError, import_tf_graph, supported_tf_ops)

__all__ = ["KerasModelImport", "import_keras_model_and_weights",
           "import_keras_sequential_model_and_weights",
           "TFImportError", "import_tf_graph", "supported_tf_ops"]
