"""Model import (reference: deeplearning4j-modelimport + samediff-import).

Keras .h5 → layer-API networks. TF/ONNX graph import arrives separately.
"""
from deeplearning4j_tpu.modelimport.keras_import import (
    KerasModelImport, import_keras_model_and_weights,
    import_keras_sequential_model_and_weights)

__all__ = ["KerasModelImport", "import_keras_model_and_weights",
           "import_keras_sequential_model_and_weights"]
