"""TensorFlow GraphDef schema views over the protowire decoder.

Reference parity: the reference parses TF protos with generated bindings
(org.nd4j.ir + tensorflow protos; TFGraphMapper.java:56 walks NodeDef/
AttrValue/TensorProto). Field numbers below are the public, frozen schema of
tensorflow/core/framework/{graph,node_def,attr_value,tensor,tensor_shape,
types}.proto — schema constants, not code.

GraphDef:        node=1, library=2, versions=4
NodeDef:         name=1, op=2, input=3, device=4, attr=5 (map entry: key=1, value=2)
AttrValue:       list=1, s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8, func=10
AttrValue.ListValue: s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8
NameAttrList:    name=1, attr=2 (map entry: key=1, value=2)
FunctionDefLibrary: function=1, gradient=2
FunctionDef:     signature=1 (OpDef), node_def=3, ret=4 (map), attr=5
OpDef:           name=1, input_arg=2, output_arg=3 (ArgDef: name=1, type=3,
                 type_attr=4)
TensorProto:     dtype=1, tensor_shape=2, tensor_content=4, half_val=13,
                 float_val=5, double_val=6, int_val=7, string_val=8,
                 int64_val=10, bool_val=11, uint32_val=16, uint64_val=17
TensorShapeProto: dim=2 (size=1, name=2), unknown_rank=3
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.modelimport.protowire import Fields

# tensorflow/core/framework/types.proto DataType enum (public constants)
TF_DTYPES: Dict[int, Optional[np.dtype]] = {
    1: np.dtype(np.float32),    # DT_FLOAT
    2: np.dtype(np.float64),    # DT_DOUBLE
    3: np.dtype(np.int32),      # DT_INT32
    4: np.dtype(np.uint8),      # DT_UINT8
    5: np.dtype(np.int16),      # DT_INT16
    6: np.dtype(np.int8),       # DT_INT8
    7: None,                    # DT_STRING (handled separately)
    9: np.dtype(np.int64),      # DT_INT64
    10: np.dtype(np.bool_),     # DT_BOOL
    14: None,                   # DT_BFLOAT16 (np has no bf16; via ml_dtypes)
    17: np.dtype(np.uint16),    # DT_UINT16
    19: np.dtype(np.float16),   # DT_HALF
    22: np.dtype(np.uint32),    # DT_UINT32
    23: np.dtype(np.uint64),    # DT_UINT64
}


def tf_dtype_to_np(enum: int) -> np.dtype:
    if enum == 14:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    dt = TF_DTYPES.get(enum)
    if dt is None:
        raise ValueError(f"unsupported TF dtype enum {enum}")
    return dt


def decode_shape(shape_fields: Optional[Fields]) -> Optional[List[int]]:
    """TensorShapeProto -> [dims] with -1 for unknown; None if unknown rank."""
    if shape_fields is None:
        return []
    if shape_fields.boolean(3):   # unknown_rank
        return None
    dims = []
    for d in shape_fields.repeated_message(2):
        dims.append(d.svarint(1, 0))
    return dims


def decode_tensor(t: Fields) -> np.ndarray:
    """TensorProto -> numpy array."""
    dtype_enum = t.varint(1)
    shape = decode_shape(t.message(2)) or []
    if dtype_enum == 7:  # DT_STRING
        vals = [b.decode("utf-8", "replace") for b in t.repeated_bytes(8)]
        return np.array(vals, dtype=object).reshape(shape)
    np_dtype = tf_dtype_to_np(dtype_enum)
    content = t.bytes_(4)
    n = int(np.prod(shape)) if shape else 1
    if content:
        arr = np.frombuffer(content, dtype=np_dtype).copy()
        return arr.reshape(shape)
    # typed value fields (possibly length 1 broadcast to shape)
    if dtype_enum == 1:
        vals = np.array(t.repeated_f32(5), dtype=np.float32)
    elif dtype_enum == 2:
        vals = np.array(t.repeated_f64(6), dtype=np.float64)
    elif dtype_enum in (3, 4, 5, 6, 17):
        vals = np.array(t.repeated_svarint(7), dtype=np_dtype)
    elif dtype_enum == 9:
        vals = np.array(t.repeated_svarint(10), dtype=np.int64)
    elif dtype_enum == 10:
        vals = np.array([bool(v) for v in t.repeated_varint(11)], dtype=np.bool_)
    elif dtype_enum == 19:  # half stored as repeated int (bit patterns)
        bits = np.array(t.repeated_varint(13), dtype=np.uint16)
        vals = bits.view(np.float16)
    elif dtype_enum == 14:  # bfloat16 bit patterns
        import ml_dtypes
        bits = np.array(t.repeated_varint(13), dtype=np.uint16)
        vals = bits.view(ml_dtypes.bfloat16)
    elif dtype_enum in (22, 23):
        vals = np.array(t.repeated_varint(16 if dtype_enum == 22 else 17),
                        dtype=np_dtype)
    else:
        raise ValueError(f"cannot decode TensorProto dtype {dtype_enum}")
    if vals.size == 0:
        return np.zeros(shape, np_dtype)
    if vals.size == 1 and n > 1:   # splat encoding
        return np.full(shape, vals[0], dtype=np_dtype)
    return vals.reshape(shape)


class AttrValue:
    """One NodeDef attribute."""

    def __init__(self, fields: Fields):
        self._f = fields

    @property
    def s(self) -> str:
        return self._f.bytes_(2).decode("utf-8", "replace")

    @property
    def i(self) -> int:
        return self._f.svarint(3)

    @property
    def f(self) -> float:
        return self._f.f32(4)

    @property
    def b(self) -> bool:
        return self._f.boolean(5)

    @property
    def type(self) -> int:
        return self._f.varint(6)

    @property
    def shape(self) -> Optional[List[int]]:
        return decode_shape(self._f.message(7))

    @property
    def tensor(self) -> np.ndarray:
        m = self._f.message(8)
        if m is None:
            raise ValueError("attr has no tensor")
        return decode_tensor(m)

    @property
    def func(self) -> Optional[str]:
        """NameAttrList.name — the FunctionDef a While/If node's
        cond/body/then_branch/else_branch attr points at."""
        m = self._f.message(10)
        return m.string(1) if m is not None else None

    @property
    def list(self) -> Dict[str, list]:
        lv = self._f.message(1)
        if lv is None:
            return {"s": [], "i": [], "f": [], "b": [], "type": [], "shape": []}
        return {
            "s": [b.decode("utf-8", "replace") for b in lv.repeated_bytes(2)],
            "i": lv.repeated_svarint(3),
            "f": lv.repeated_f32(4),
            "b": [bool(v) for v in lv.repeated_varint(5)],
            "type": lv.repeated_varint(6),
            "shape": [decode_shape(s) for s in lv.repeated_message(7)],
        }


class NodeDef:
    def __init__(self, fields: Fields):
        self.name = fields.string(1)
        self.op = fields.string(2)
        self.inputs = fields.repeated_string(3)
        self.attrs: Dict[str, AttrValue] = {}
        for entry in fields.repeated_message(5):
            key = entry.string(1)
            val = entry.message(2)
            if val is not None:
                self.attrs[key] = AttrValue(val)

    def attr(self, name: str) -> Optional[AttrValue]:
        return self.attrs.get(name)

    def __repr__(self):
        return f"NodeDef({self.op} {self.name!r} inputs={self.inputs})"


class ArgDef:
    def __init__(self, fields: Fields):
        self.name = fields.string(1)
        self.type = fields.varint(3)        # DataType enum (0 if type_attr)
        self.type_attr = fields.string(4)


class FunctionDef:
    """tensorflow.FunctionDef — the subgraph a TF2 functional
    While/If node invokes."""

    def __init__(self, fields: Fields):
        sig = fields.message(1)
        self.name = sig.string(1) if sig else ""
        self.input_args: List[ArgDef] = (
            [ArgDef(a) for a in sig.repeated_message(2)] if sig else [])
        self.output_args: List[ArgDef] = (
            [ArgDef(a) for a in sig.repeated_message(3)] if sig else [])
        self.nodes: List[NodeDef] = [NodeDef(f)
                                     for f in fields.repeated_message(3)]
        self.ret: Dict[str, str] = {}
        for entry in fields.repeated_message(4):
            self.ret[entry.string(1)] = entry.string(2)


class GraphDef:
    def __init__(self, data: bytes):
        fields = Fields(data)
        self.nodes: List[NodeDef] = [NodeDef(f) for f in fields.repeated_message(1)]
        self.functions: Dict[str, FunctionDef] = {}
        lib = fields.message(2)
        if lib is not None:
            for f in lib.repeated_message(1):
                fd = FunctionDef(f)
                self.functions[fd.name] = fd

    @staticmethod
    def from_file(path: str) -> "GraphDef":
        with open(path, "rb") as fh:
            return GraphDef(fh.read())
