"""TensorFlow frozen-GraphDef → SameDiff importer.

Reference parity: ImportGraph.importGraph (samediff-import-api/src/main/
kotlin/org/nd4j/samediff/frameworkimport/ImportGraph.kt:218) and the legacy
TFGraphMapper (nd4j-api/.../imports/graphmapper/tf/TFGraphMapper.java:56):
walk GraphDef.node, resolve Const/Placeholder/control inputs/`name:i`
output refs, and map each NodeDef (op + attrs) onto framework ops. The
op-name table mirrors ImportClassMapping.java:40's role.

TPU-native redesign: XLA wants static shapes, so the importer CONST-FOLDS
every structural tensor (Reshape shapes, reduce axes, StridedSlice specs,
Range/Fill dims) at import time and emits registry ops with *static attrs* —
the traced graph stays purely data-flow and jit-compiles to one XLA
computation. TF `Shape` nodes resolve against the static shapes flowing
through the import (batch dims must be concrete for shape-math folding; the
usual frozen-graph pattern Shape→StridedSlice→Pack→Reshape folds away
entirely). Control inputs (`^node`) order side effects in TF; every emitted
op here is pure, so they are dropped.

Weights come in as CONSTANTs by default (inference import). With
``trainable="auto"`` floating-point consts of rank>=1 become VARIABLEs —
the fine-tuning path (BASELINE config 4's BERT fine-tune step); a predicate
``trainable=lambda name, arr: ...`` gives explicit control.

SCOPE — which of tensorflow-op-def.pbtxt's ~1200 op families import
(the reference's registry: samediff-import-tensorflow/src/main/resources/
tensorflow-op-def.pbtxt; its own mapper covers a comparable subset):

IN SCOPE (~130 NodeDef ops + functional control flow):
- math/elementwise/reduction/linalg/nn/conv/pool/image resize families
  (see supported_tf_ops() for the authoritative list);
- structural ops (Reshape/StridedSlice/Concat/Pack/Shape/Range/Fill...)
  with CONST-FOLDABLE arguments — the frozen-inference-graph pattern;
- TF2 *functional* control flow: StatelessWhile/While, StatelessIf/If
  with FunctionDef library bodies -> lax.while_loop / lax.cond
  (data-dependent trip counts run on-device; While output is
  forward-only for AD — record with SameDiff.scan for trainable
  recurrence);
- Placeholder shape handling: shape attrs auto-derive placeholder
  shapes; shape=None / -1 dims raise an actionable error naming
  ``input_shapes=`` when shape math needs them.

OUT OF SCOPE (by design — raise TFImportError):
- v1 control-flow frames (Enter/Exit/Switch/Merge/NextIteration,
  LoopCond): the pre-TF2 cyclic-graph encoding; freeze with TF2
  functional ops instead (the reference's ADR 0020 makes the same
  break);
- stateful/resource ops (Variable/VarHandleOp/ReadVariableOp/Assign*,
  queues, iterators, datasets, StackV2/TensorArrayV3): a frozen graph
  has no mutable state; run the TF freezing tools first;
- data-dependent *shapes* (Where, NonMaxSuppression's dynamic output,
  boolean_mask composites, Unique as a data input): XLA requires
  static shapes; these need host-side execution by construction;
- string/audio/sparse/ragged families, summary/debug ops, and
  gradient-helper ops (the importer consumes inference graphs;
  training graphs re-derive gradients via jax.grad after import).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.variable import SDVariable
from deeplearning4j_tpu.modelimport.tf_pb import (
    GraphDef, NodeDef, tf_dtype_to_np)
from deeplearning4j_tpu.ops import registry


class TFImportError(ValueError):
    pass


class _Val:
    """One TF tensor during import: a graph variable and/or a folded
    numpy constant (structural values keep the constant side)."""

    __slots__ = ("var", "const", "_name")

    def __init__(self, var=None, const=None, name=""):
        self.var = var
        self.const = const
        self._name = name

    @property
    def is_const(self):
        return self.const is not None


def _split_ref(ref: str) -> Tuple[str, Optional[str], int]:
    """One parser for every tensor-ref form -> (name, out_arg, idx):
    'node' -> (node, None, 0); 'node:2' -> (node, None, 2);
    FunctionDef bodies: 'node:z:1' -> (node, 'z', 1) and the shorthand
    'node:z' -> (node, 'z', 0). The out_arg index is WITHIN the named
    arg; _resolve maps it to a flat index via the producer's layout."""
    parts = ref.split(":")
    if len(parts) == 3:
        return parts[0], parts[1], int(parts[2])
    if len(parts) == 2:
        if parts[1].isdigit():
            return parts[0], None, int(parts[1])
        return parts[0], parts[1], 0
    return ref, None, 0


def _norm_ref(ref: str) -> Tuple[str, int]:
    """Plain-GraphDef ref -> (node, flat idx). Named-arg refs (only
    legal inside FunctionDef bodies) must resolve through _resolve's
    layout logic — treating them as index 0 here would silently pick
    the wrong tensor of a multi-output op."""
    name, arg, sub = _split_ref(ref)
    if arg is not None:
        raise TFImportError(
            f"named output-arg ref {ref!r} needs producer layout "
            f"resolution (FunctionDef-body form); plain GraphDef refs "
            f"are 'node' or 'node:<int>'")
    return name, sub


class TFImporter:
    """Imports one GraphDef; see import_tf_graph() for the entry point."""

    def __init__(self, graph: GraphDef,
                 trainable: Union[None, str, Callable] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None):
        self.graph = graph
        self.sd = SameDiff()
        self.input_shapes = dict(input_shapes or {})
        self._tensors: Dict[Tuple[str, int], _Val] = {}
        self._nodes: Dict[str, NodeDef] = {n.name: n for n in graph.nodes}
        if trainable == "auto":
            self._trainable = lambda name, arr: (
                np.issubdtype(arr.dtype, np.floating) and arr.ndim >= 1)
        elif callable(trainable):
            self._trainable = trainable
        else:
            self._trainable = lambda name, arr: False
        self.placeholder_names: List[str] = []
        self.variable_names: List[str] = []
        # PlaceholderWithDefault nodes bound to their constant default
        self.placeholder_defaults: Dict[str, np.ndarray] = {}
        # placeholders whose pb shape attr is absent/unknown-rank/-1-dim
        # and that input_shapes= did not pin (shape-math import errors
        # name these so the fix is one kwarg away)
        self.underspecified_placeholders: Dict[str, Optional[Sequence[int]]] = {}

    # ------------------------------------------------------------------
    def run(self) -> SameDiff:
        for node in self._topo_order():
            try:
                self._import_node(node)
            except TFImportError:
                raise
            except Exception as e:
                raise TFImportError(
                    f"while importing node {node.op} {node.name!r}: {e}") from e
        return self.sd

    def _topo_order(self) -> List[NodeDef]:
        """Kahn topo sort on data deps (GraphDef node order is arbitrary)."""
        indeg: Dict[str, int] = {}
        consumers: Dict[str, List[str]] = {}
        for n in self.graph.nodes:
            deps = {_split_ref(i.lstrip("^"))[0] for i in n.inputs}
            deps = {d for d in deps if d in self._nodes and d != n.name}
            indeg[n.name] = len(deps)
            for d in deps:
                consumers.setdefault(d, []).append(n.name)
        ready = [n.name for n in self.graph.nodes if indeg[n.name] == 0]
        order: List[NodeDef] = []
        seen = set()
        while ready:
            nm = ready.pop()
            if nm in seen:
                continue
            seen.add(nm)
            order.append(self._nodes[nm])
            for c in consumers.get(nm, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.graph.nodes):
            stuck = [n for n in indeg if n not in seen]
            raise TFImportError(f"graph has a dataflow cycle (or v1 control "
                                f"flow frames): unplaced nodes {stuck[:5]}")
        return order

    # ------------------------------------------------------------------
    # input resolution
    def _resolve(self, ref: str) -> _Val:
        name, arg, sub = _split_ref(ref)
        if arg is None:
            idx = sub
        else:
            # FunctionDef-body ref: idx is WITHIN the named output arg;
            # the flat index needs the producer's output-arg layout
            node = self._nodes.get(name)
            layout = _FUNC_OUT_ARGS.get(node.op) if node is not None else None
            if layout is not None:
                if arg not in layout:
                    raise TFImportError(
                        f"function-body ref {ref!r}: unknown output arg "
                        f"{arg!r} of {node.op} (known: {layout})")
                idx = layout.index(arg) + sub
            else:
                # single-output-arg producer (or an arg placeholder):
                # within-arg index IS the flat index — but refuse to
                # guess whenever the producer recorded several outputs
                # and we have no layout for it
                idx = sub
                if (name, 1) in self._tensors and node is not None and \
                        arg not in ("output", "z", "y", "out"):
                    raise TFImportError(
                        f"function-body ref {ref!r}: {node.op} has "
                        f"multiple outputs and no known output-arg "
                        f"layout; cannot map {arg!r} to a flat index")
        try:
            return self._tensors[(name, idx)]
        except KeyError:
            raise TFImportError(
                f"input {ref!r} not produced by any imported node") from None

    def _ins(self, node: NodeDef) -> List[_Val]:
        return [self._resolve(r) for r in node.inputs if not r.startswith("^")]

    def _set(self, name: str, outs: Sequence[_Val]):
        for i, v in enumerate(outs):
            self._tensors[(name, i)] = v

    def _materialize(self, v: _Val) -> SDVariable:
        """Graph variable for a value; folded constants become sd.constant
        lazily (first data use)."""
        if v.var is None:
            v.var = self.sd.constant(np.asarray(v.const), name=v._name or "imported_const")
        return v.var

    # static helpers for structural args -------------------------------
    def _const_np(self, v: _Val, what: str) -> np.ndarray:
        if not v.is_const:
            raise TFImportError(
                f"{what} must be trace-time constant (derived from consts "
                f"and static shapes); got a data-dependent tensor")
        return np.asarray(v.const)

    def _ints(self, v: _Val, what: str) -> Tuple[int, ...]:
        return tuple(int(x) for x in self._const_np(v, what).reshape(-1))

    def _int1(self, v: _Val, what: str) -> int:
        return int(self._const_np(v, what).reshape(()))

    # ------------------------------------------------------------------
    def emit(self, op_name: str, ins: Sequence[_Val], attrs: Dict,
             name: str, n_outputs: int = 1) -> List[_Val]:
        """Emit a registry op — or fold it eagerly when every input is
        constant (constant-propagation; keeps Shape-math and frozen
        weight-preprocessing out of the runtime graph)."""
        if all(v.is_const for v in ins):
            fn = registry.get_op(op_name).fn
            res = fn(*[np.asarray(v.const) for v in ins], **attrs)
            res = res if isinstance(res, (tuple, list)) else [res]
            return [_Val(const=np.asarray(r), name=f"{name}:{i}" if i else name)
                    for i, r in enumerate(res)]
        vars_ = [self._materialize(v) for v in ins]
        out = self.sd.invoke(op_name, vars_, attrs=attrs, name=name,
                             n_outputs=n_outputs)
        outs = out if isinstance(out, list) else [out]
        return [_Val(var=o) for o in outs]

    def _static_shape(self, v: _Val, node_name: str) -> Tuple[int, ...]:
        if v.is_const:
            return tuple(np.asarray(v.const).shape)
        shape = v.var.shape
        if shape is None or any(d is None or d < 0 for d in shape):
            hint = ""
            if self.underspecified_placeholders:
                ex = ", ".join(
                    f"{n!r}: (batch, ...)"
                    for n in sorted(self.underspecified_placeholders))
                hint = (f" — this graph's placeholders carry no static "
                        f"shape in the pb (a normal frozen-graph export "
                        f"artifact): pass input_shapes={{{ex}}} with "
                        f"concrete dims")
            raise TFImportError(
                f"Shape node {node_name!r}: input has non-static shape "
                f"{shape}{hint}")
        return tuple(shape)

    # ------------------------------------------------------------------
    def _import_node(self, node: NodeDef):
        op = node.op
        if op == "NoOp":
            return
        if op == "Const":
            arr = node.attrs["value"].tensor
            if self._trainable(node.name, arr):
                var = self.sd.var(node.name, value=arr,
                                  dtype=str(arr.dtype))
                self.variable_names.append(var.name)
                self._set(node.name, [_Val(var=var)])
            else:
                self._set(node.name, [_Val(const=arr, name=node.name)])
            return
        if op == "PlaceholderWithDefault":
            # a static graph can't be "fed or defaulted" both ways; frozen-
            # graph semantics (keep_prob flags etc.) want the default, so a
            # constant default imports as that constant. The value is kept
            # in placeholder_defaults so callers can see what was bound; a
            # data-dependent default falls through to a real placeholder.
            ins = self._ins(node)
            if ins and ins[0].is_const:
                self.placeholder_defaults[node.name] = np.asarray(ins[0].const)
                self._set(node.name, [_Val(const=np.asarray(ins[0].const),
                                           name=node.name)])
                return
        if op in ("Placeholder", "PlaceholderWithDefault"):
            a = node.attr("shape")
            shape = self.input_shapes.get(node.name)
            if shape is None and a is not None:
                shape = a.shape          # auto-derive from the shape attr
            if shape is None or any(d is None or d < 0 for d in (shape or ())):
                # real frozen graphs routinely carry shape=None / dim=-1
                # placeholders (the exporter never pinned them); record it
                # so shape-dependent failures can name the fix
                self.underspecified_placeholders[node.name] = shape
            dt = node.attr("dtype")
            np_dt = tf_dtype_to_np(dt.type) if dt else np.dtype(np.float32)
            ph = self.sd.placeholder(node.name, shape=shape, dtype=str(np_dt))
            self.placeholder_names.append(ph.name)
            self._set(node.name, [_Val(var=ph)])
            return

        mapper = _MAPPERS.get(op)
        if mapper is None:
            raise TFImportError(
                f"unmapped TF op {op!r} (node {node.name!r}); "
                f"{len(_MAPPERS)} ops supported")
        outs = mapper(self, node, self._ins(node))
        if isinstance(outs, _Val):
            outs = [outs]
        self._set(node.name, outs)


# ---------------------------------------------------------------------------
# mapper table (reference: ImportClassMapping.java:40's name->class table)
_MAPPERS: Dict[str, Callable] = {}

# output-arg layout of mapped multi-output ops whose args are each size 1
# (FunctionDef refs name the arg: 'topk:indices:0' -> flat index 1).
# Split/SplitV/While/If expose ONE size-N arg ('output'), where the
# within-arg index already equals the flat index.
_FUNC_OUT_ARGS: Dict[str, Tuple[str, ...]] = {
    "TopKV2": ("values", "indices"),
    "FusedBatchNorm": ("y", "batch_mean", "batch_variance"),
    "FusedBatchNormV2": ("y", "batch_mean", "batch_variance"),
    "FusedBatchNormV3": ("y", "batch_mean", "batch_variance",
                         "reserve_space_1", "reserve_space_2",
                         "reserve_space_3"),
}


def _mapper(*tf_names):
    def deco(fn):
        for n in tf_names:
            _MAPPERS[n] = fn
        return fn
    return deco


def _attr_b(node, name, default=False):
    a = node.attr(name)
    return a.b if a is not None else default


def _attr_i(node, name, default=0):
    a = node.attr(name)
    return a.i if a is not None else default


def _attr_f(node, name, default=0.0):
    a = node.attr(name)
    return a.f if a is not None else default


def _attr_s(node, name, default=""):
    a = node.attr(name)
    return a.s if a is not None else default


def _attr_ilist(node, name, default=()):
    a = node.attr(name)
    return list(a.list["i"]) if a is not None else list(default)


def _attr_type(node, name, default: int):
    """DataType attr (Cast DstT, ArgMax output_type, Shape out_type, ...).

    TF serializes these as AttrValue.type (field 6); graphs written by
    tf_builder may carry a plain int (field 3) — accept both."""
    a = node.attr(name)
    if a is None:
        return default
    return a.type or a.i or default


# --- passthrough / identity ------------------------------------------------
@_mapper("Identity", "Snapshot", "PreventGradient", "CheckNumerics",
         "EnsureShape")
def _m_identity(imp, node, ins):
    return ins[0]


@_mapper("IdentityN")
def _m_identity_n(imp, node, ins):
    return list(ins)


@_mapper("StopGradient")
def _m_stop_gradient(imp, node, ins):
    return imp.emit("stop_gradient", ins, {}, node.name)


# --- unary elementwise -----------------------------------------------------
_UNARY = {
    "Relu": "relu", "Relu6": "relu6", "Elu": "elu", "Selu": "selu",
    "Softplus": "softplus", "Softsign": "softsign", "Sigmoid": "sigmoid",
    "Tanh": "tanh", "Exp": "exp", "Log": "log", "Log1p": "log1p",
    "Sqrt": "sqrt", "Rsqrt": "rsqrt", "Square": "square", "Abs": "abs",
    "Neg": "neg", "Sign": "sign", "Floor": "floor", "Ceil": "ceil",
    "Round": "round", "Rint": "rint", "Erf": "erf", "Erfc": "erfc",
    "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin",
    "Acos": "acos", "Atan": "atan", "Sinh": "sinh", "Cosh": "cosh",
    "Asinh": "asinh", "Acosh": "acosh", "Atanh": "atanh",
    "Reciprocal": "reciprocal", "Inv": "reciprocal", "Expm1": "expm1",
    "Digamma": "digamma", "Lgamma": "lgamma", "LogicalNot": "not",
    "IsNan": "isnan", "IsInf": "isinf", "IsFinite": "isfinite",
}


def _make_unary(reg_name):
    def m(imp, node, ins):
        return imp.emit(reg_name, ins, {}, node.name)
    return m


for _tf, _reg in _UNARY.items():
    _MAPPERS[_tf] = _make_unary(_reg)


@_mapper("LeakyRelu")
def _m_leaky_relu(imp, node, ins):
    return imp.emit("leaky_relu", ins, {"alpha": _attr_f(node, "alpha", 0.2)},
                    node.name)


@_mapper("Softmax")
def _m_softmax(imp, node, ins):
    return imp.emit("softmax", ins, {"axis": -1}, node.name)


@_mapper("LogSoftmax")
def _m_log_softmax(imp, node, ins):
    return imp.emit("log_softmax", ins, {"axis": -1}, node.name)


# --- binary elementwise ----------------------------------------------------
_BINARY = {
    "Add": "add", "AddV2": "add", "Sub": "subtract", "Mul": "multiply",
    "Div": "divide", "RealDiv": "divide", "DivNoNan": "divide_no_nan",
    "FloorDiv": "floordiv", "FloorMod": "floormod", "Mod": "mod",
    "Maximum": "maximum", "Minimum": "minimum", "Pow": "pow_pairwise",
    "SquaredDifference": "squaredsubtract", "Atan2": "atan2",
    "Equal": "equals", "NotEqual": "not_equals", "Greater": "greater",
    "GreaterEqual": "greater_equal", "Less": "less",
    "LessEqual": "less_equal", "LogicalAnd": "boolean_and",
    "LogicalOr": "boolean_or", "TruncateDiv": "truncatediv",
    "Igamma": "igamma", "Igammac": "igammac", "Hypot": "hypot",
}


def _make_binary(reg_name):
    def m(imp, node, ins):
        return imp.emit(reg_name, ins, {}, node.name)
    return m


for _tf, _reg in _BINARY.items():
    _MAPPERS[_tf] = _make_binary(_reg)


@_mapper("AddN", "AccumulateNV2")
def _m_addn(imp, node, ins):
    return imp.emit("tf_addn", ins, {}, node.name)


@_mapper("Select", "SelectV2")
def _m_select(imp, node, ins):
    return imp.emit("where_op", ins, {}, node.name)


@_mapper("ClipByValue")
def _m_clip(imp, node, ins):
    lo = imp._const_np(ins[1], "ClipByValue min")
    hi = imp._const_np(ins[2], "ClipByValue max")
    return imp.emit("clip_by_value", [ins[0]],
                    {"clip_min": float(lo), "clip_max": float(hi)}, node.name)


# --- matmul family ---------------------------------------------------------
@_mapper("MatMul")
def _m_matmul(imp, node, ins):
    return imp.emit("matmul", ins,
                    {"transpose_a": _attr_b(node, "transpose_a"),
                     "transpose_b": _attr_b(node, "transpose_b")}, node.name)


@_mapper("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _m_batch_matmul(imp, node, ins):
    return imp.emit("batched_matmul", ins,
                    {"transpose_a": _attr_b(node, "adj_x"),
                     "transpose_b": _attr_b(node, "adj_y")}, node.name)


@_mapper("Einsum")
def _m_einsum(imp, node, ins):
    return imp.emit("einsum", ins, {"equation": _attr_s(node, "equation")},
                    node.name)


@_mapper("BiasAdd")
def _m_bias_add(imp, node, ins):
    return imp.emit("bias_add", ins,
                    {"data_format": _attr_s(node, "data_format", "NHWC")},
                    node.name)


@_mapper("L2Loss")
def _m_l2_loss(imp, node, ins):
    sq = imp.emit("square", ins, {}, node.name + "/sq")
    s = imp.emit("reduce_sum", sq, {}, node.name + "/sum")
    return imp.emit("scalar_mul", s, {"scalar": 0.5}, node.name)


# --- conv / pool / norm ----------------------------------------------------
@_mapper("Conv2D")
def _m_conv2d(imp, node, ins):
    df = _attr_s(node, "data_format", "NHWC")
    strides = _attr_ilist(node, "strides", (1, 1, 1, 1))
    dil = _attr_ilist(node, "dilations", (1, 1, 1, 1))
    sp = (1, 2) if df == "NHWC" else (2, 3)
    return imp.emit("conv2d", ins, {
        "strides": (strides[sp[0]], strides[sp[1]]),
        "dilation": (dil[sp[0]], dil[sp[1]]),
        "padding": _attr_s(node, "padding", "SAME"),
        "data_format": df}, node.name)


@_mapper("DepthwiseConv2dNative")
def _m_depthwise_conv2d(imp, node, ins):
    df = _attr_s(node, "data_format", "NHWC")
    strides = _attr_ilist(node, "strides", (1, 1, 1, 1))
    sp = (1, 2) if df == "NHWC" else (2, 3)
    return imp.emit("depthwise_conv2d", ins, {
        "strides": (strides[sp[0]], strides[sp[1]]),
        "padding": _attr_s(node, "padding", "SAME"),
        "data_format": df}, node.name)


def _pool(imp, node, ins, reg_name):
    df = _attr_s(node, "data_format", "NHWC")
    ks = _attr_ilist(node, "ksize", (1, 2, 2, 1))
    st = _attr_ilist(node, "strides", (1, 2, 2, 1))
    sp = (1, 2) if df == "NHWC" else (2, 3)
    return imp.emit(reg_name, ins, {
        "kernel": (ks[sp[0]], ks[sp[1]]),
        "strides": (st[sp[0]], st[sp[1]]),
        "padding": _attr_s(node, "padding", "VALID"),
        "data_format": df}, node.name)


@_mapper("MaxPool")
def _m_max_pool(imp, node, ins):
    return _pool(imp, node, ins, "max_pool2d")


@_mapper("AvgPool")
def _m_avg_pool(imp, node, ins):
    return _pool(imp, node, ins, "avg_pool2d")


@_mapper("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _m_fused_batch_norm(imp, node, ins):
    outs = imp.emit("tf_fused_batch_norm", ins, {
        "epsilon": _attr_f(node, "epsilon", 1e-3),
        "data_format": _attr_s(node, "data_format", "NHWC"),
        "is_training": _attr_b(node, "is_training", False)},
        node.name, n_outputs=3)
    # V3 declares 6 outputs (y, mean, var, 3 reserve spaces); reserves are
    # only consumed by the TF-side grad op — alias them to mean/var
    return outs + [outs[1], outs[2], outs[1]]


@_mapper("LRN")
def _m_lrn(imp, node, ins):
    return imp.emit("lrn", ins, {
        "depth": _attr_i(node, "depth_radius", 5),
        "bias": _attr_f(node, "bias", 1.0),
        "alpha": _attr_f(node, "alpha", 1.0),
        "beta": _attr_f(node, "beta", 0.5),
        "data_format": "NHWC"}, node.name)


# --- shape / structure (structural args const-folded) ----------------------
@_mapper("Shape")
def _m_shape(imp, node, ins):
    shape = imp._static_shape(ins[0], node.name)
    out_dt = tf_dtype_to_np(_attr_type(node, "out_type", 3))
    return _Val(const=np.asarray(shape, dtype=out_dt), name=node.name)


@_mapper("ShapeN")
def _m_shape_n(imp, node, ins):
    out_dt = tf_dtype_to_np(_attr_type(node, "out_type", 3))
    return [_Val(const=np.asarray(imp._static_shape(v, node.name), out_dt))
            for v in ins]


@_mapper("Size")
def _m_size(imp, node, ins):
    shape = imp._static_shape(ins[0], node.name)
    return _Val(const=np.asarray(int(np.prod(shape)), dtype=np.int32))


@_mapper("Rank")
def _m_rank(imp, node, ins):
    shape = imp._static_shape(ins[0], node.name)
    return _Val(const=np.asarray(len(shape), dtype=np.int32))


@_mapper("Reshape")
def _m_reshape(imp, node, ins):
    shape = imp._ints(ins[1], "Reshape shape")
    return imp.emit("reshape", [ins[0]], {"shape": shape}, node.name)


@_mapper("Transpose")
def _m_transpose(imp, node, ins):
    perm = imp._ints(ins[1], "Transpose perm")
    return imp.emit("permute", [ins[0]], {"axes": perm}, node.name)


@_mapper("ExpandDims")
def _m_expand_dims(imp, node, ins):
    axis = imp._int1(ins[1], "ExpandDims dim")
    return imp.emit("expand_dims", [ins[0]], {"axis": axis}, node.name)


@_mapper("Squeeze")
def _m_squeeze(imp, node, ins):
    dims = _attr_ilist(node, "squeeze_dims") or _attr_ilist(node, "axis")
    return imp.emit("squeeze", [ins[0]],
                    {"axis": tuple(dims) if dims else None}, node.name)


@_mapper("ConcatV2")
def _m_concat_v2(imp, node, ins):
    axis = imp._int1(ins[-1], "ConcatV2 axis")
    return imp.emit("concat", ins[:-1], {"axis": axis}, node.name)


@_mapper("Concat")
def _m_concat(imp, node, ins):
    axis = imp._int1(ins[0], "Concat axis")   # legacy: axis FIRST
    return imp.emit("concat", ins[1:], {"axis": axis}, node.name)


@_mapper("Pack")
def _m_pack(imp, node, ins):
    return imp.emit("stack", ins, {"axis": _attr_i(node, "axis", 0)},
                    node.name)


@_mapper("Unpack")
def _m_unpack(imp, node, ins):
    num = _attr_i(node, "num", 1)
    return imp.emit("unstack", ins, {"axis": _attr_i(node, "axis", 0)},
                    node.name, n_outputs=num)


@_mapper("Split")
def _m_split(imp, node, ins):
    axis = imp._int1(ins[0], "Split axis")    # (axis, value) input order
    num = _attr_i(node, "num_split", 1)
    return imp.emit("split", [ins[1]], {"num_split": num, "axis": axis},
                    node.name, n_outputs=num)


@_mapper("SplitV")
def _m_split_v(imp, node, ins):
    sizes = imp._ints(ins[1], "SplitV size_splits")
    axis = imp._int1(ins[2], "SplitV axis")
    return imp.emit("split_v", [ins[0]], {"sizes": sizes, "axis": axis},
                    node.name, n_outputs=len(sizes))


@_mapper("StridedSlice")
def _m_strided_slice(imp, node, ins):
    return imp.emit("strided_slice_masked", [ins[0]], {
        "begin": imp._ints(ins[1], "StridedSlice begin"),
        "end": imp._ints(ins[2], "StridedSlice end"),
        "strides": imp._ints(ins[3], "StridedSlice strides"),
        "begin_mask": _attr_i(node, "begin_mask"),
        "end_mask": _attr_i(node, "end_mask"),
        "ellipsis_mask": _attr_i(node, "ellipsis_mask"),
        "new_axis_mask": _attr_i(node, "new_axis_mask"),
        "shrink_axis_mask": _attr_i(node, "shrink_axis_mask")}, node.name)


@_mapper("Slice")
def _m_slice(imp, node, ins):
    begin = imp._ints(ins[1], "Slice begin")
    size = imp._ints(ins[2], "Slice size")
    return imp.emit("slice", [ins[0]], {"begin": begin, "size": size},
                    node.name)


@_mapper("Gather", "GatherV2")
def _m_gather(imp, node, ins):
    axis = imp._int1(ins[2], "Gather axis") if len(ins) > 2 else 0
    bd = _attr_i(node, "batch_dims", 0)
    if bd:
        return imp.emit("gather_batch_dims", ins[:2],
                        {"axis": axis, "batch_dims": bd}, node.name)
    return imp.emit("gather", ins[:2], {"axis": axis}, node.name)


@_mapper("GatherNd")
def _m_gather_nd(imp, node, ins):
    return imp.emit("gather_nd", ins, {}, node.name)


@_mapper("OneHot")
def _m_one_hot(imp, node, ins):
    depth = imp._int1(ins[1], "OneHot depth")
    on = float(imp._const_np(ins[2], "OneHot on_value"))
    off = float(imp._const_np(ins[3], "OneHot off_value"))
    dt = node.attr("T")
    return imp.emit("one_hot", [ins[0]], {
        "depth": depth, "on_value": on, "off_value": off,
        "axis": _attr_i(node, "axis", -1),
        "dtype": str(tf_dtype_to_np(dt.type)) if dt else "float32"},
        node.name)


@_mapper("Fill")
def _m_fill(imp, node, ins):
    dims = imp._ints(ins[0], "Fill dims")
    if ins[1].is_const:
        value = np.asarray(ins[1].const)
        return _Val(const=np.full(dims, value), name=node.name)
    return imp.emit("broadcast_to", [ins[1]], {"shape": dims}, node.name)


@_mapper("ZerosLike")
def _m_zeros_like(imp, node, ins):
    return imp.emit("zeros_like", ins, {}, node.name)


@_mapper("OnesLike")
def _m_ones_like(imp, node, ins):
    return imp.emit("ones_like", ins, {}, node.name)


@_mapper("Range")
def _m_range(imp, node, ins):
    start = imp._const_np(ins[0], "Range start")
    limit = imp._const_np(ins[1], "Range limit")
    delta = imp._const_np(ins[2], "Range delta")
    return _Val(const=np.arange(start, limit, delta), name=node.name)


@_mapper("Tile")
def _m_tile(imp, node, ins):
    reps = imp._ints(ins[1], "Tile multiples")
    return imp.emit("tile", [ins[0]], {"reps": reps}, node.name)


@_mapper("Pad", "PadV2", "MirrorPad")
def _m_pad(imp, node, ins):
    pads = imp._const_np(ins[1], "Pad paddings").reshape(-1, 2).tolist()
    mode = "constant"
    if node.op == "MirrorPad":
        mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}[
            _attr_s(node, "mode", "REFLECT")]
    const = 0.0
    if node.op == "PadV2" and len(ins) > 2:
        const = float(imp._const_np(ins[2], "PadV2 constant_values"))
    return imp.emit("pad", [ins[0]],
                    {"paddings": pads, "mode": mode, "constant": const},
                    node.name)


@_mapper("BroadcastTo")
def _m_broadcast_to(imp, node, ins):
    shape = imp._ints(ins[1], "BroadcastTo shape")
    return imp.emit("broadcast_to", [ins[0]], {"shape": shape}, node.name)


@_mapper("Cast")
def _m_cast(imp, node, ins):
    dst = tf_dtype_to_np(_attr_type(node, "DstT", 1))
    return imp.emit("cast", ins, {"dtype": str(dst)}, node.name)


@_mapper("Reverse", "ReverseV2")
def _m_reverse(imp, node, ins):
    axis = imp._ints(ins[1], "Reverse axis")
    return imp.emit("reverse", [ins[0]], {"axis": axis}, node.name)


@_mapper("InvertPermutation")
def _m_invert_permutation(imp, node, ins):
    perm = imp._ints(ins[0], "InvertPermutation x")
    inv = np.argsort(perm).astype(np.int32)
    return _Val(const=inv, name=node.name)


# --- reductions ------------------------------------------------------------
_REDUCE = {"Mean": "reduce_mean", "Sum": "reduce_sum", "Max": "reduce_max",
           "Min": "reduce_min", "Prod": "reduce_prod", "All": "reduce_all",
           "Any": "reduce_any", "EuclideanNorm": "reduce_norm2"}


def _make_reduce(reg_name):
    def m(imp, node, ins):
        axes_np = imp._const_np(ins[1], f"{node.op} reduction_indices")
        axes = tuple(int(x) for x in axes_np.reshape(-1))
        if axes_np.ndim > 0 and len(axes) == 0:
            return ins[0]  # TF: empty axes list = identity
        return imp.emit(reg_name, [ins[0]],
                        {"axis": axes or None,
                         "keep_dims": _attr_b(node, "keep_dims", False)},
                        node.name)
    return m


for _tf, _reg in _REDUCE.items():
    _MAPPERS[_tf] = _make_reduce(_reg)


@_mapper("ArgMax")
def _m_argmax(imp, node, ins):
    axis = imp._int1(ins[1], "ArgMax dimension")
    out = imp.emit("argmax", [ins[0]], {"axis": axis}, node.name + "/arg")
    dt = tf_dtype_to_np(_attr_type(node, "output_type", 9))
    return imp.emit("cast", out, {"dtype": str(dt)}, node.name)


@_mapper("ArgMin")
def _m_argmin(imp, node, ins):
    axis = imp._int1(ins[1], "ArgMin dimension")
    out = imp.emit("argmin", [ins[0]], {"axis": axis}, node.name + "/arg")
    dt = tf_dtype_to_np(_attr_type(node, "output_type", 9))
    return imp.emit("cast", out, {"dtype": str(dt)}, node.name)


@_mapper("Cumsum")
def _m_cumsum(imp, node, ins):
    axis = imp._int1(ins[1], "Cumsum axis")
    return imp.emit("cumsum", [ins[0]], {
        "axis": axis, "exclusive": _attr_b(node, "exclusive"),
        "reverse": _attr_b(node, "reverse")}, node.name)


@_mapper("TopKV2")
def _m_top_k(imp, node, ins):
    k = imp._int1(ins[1], "TopKV2 k")
    return imp.emit("top_k", [ins[0]],
                    {"k": k, "sorted": _attr_b(node, "sorted", True)},
                    node.name, n_outputs=2)


@_mapper("SegmentSum")
def _m_segment_sum(imp, node, ins):
    seg = imp._const_np(ins[1], "SegmentSum segment_ids")
    return imp.emit("segment_sum", ins,
                    {"num_segments": int(seg.max()) + 1}, node.name)


# --- TF2 functional control flow (StatelessWhile/While, StatelessIf/If) ----
class _FuncGraph:
    """GraphDef-shaped view over one FunctionDef body (shares the outer
    graph's function library so nested control flow resolves)."""

    def __init__(self, fd, functions):
        self.nodes = fd.nodes
        self.functions = functions


def _import_function_body(imp: "TFImporter", fname: str) -> Dict:
    """FunctionDef -> control-flow subgraph dict (ops/control_flow.py
    wire format): args become placeholders, body nodes run through the
    SAME mapper table, ret refs become the subgraph outputs.

    Reference: the reference's IR maps function bodies through the same
    importGraph machinery (ImportGraph.kt:218 importing subgraphs for
    If/While per ADR 0020)."""
    from deeplearning4j_tpu.modelimport.tf_pb import tf_dtype_to_np
    from deeplearning4j_tpu.ops import control_flow as cf
    fd = imp.graph.functions.get(fname) if hasattr(imp.graph, "functions") \
        else None
    if fd is None:
        raise TFImportError(
            f"control-flow node references function {fname!r} which is "
            f"not in the GraphDef library")
    sub = TFImporter(_FuncGraph(fd, imp.graph.functions))
    for arg in fd.input_args:
        np_dt = tf_dtype_to_np(arg.type) if arg.type else np.dtype(np.float32)
        ph = sub.sd.placeholder(arg.name, shape=None, dtype=str(np_dt))
        sub.placeholder_names.append(arg.name)
        sub._set(arg.name, [_Val(var=ph)])
    sub.run()
    # weights living INSIDE a control-flow body become subgraph
    # constants — they cannot join trainable_params(), so a fine-tune
    # import (trainable='auto'/predicate) would silently freeze them.
    # Tell the user instead of training around them quietly.
    frozen = [n for n, arr in
              ((n, np.asarray(a)) for n, a in sub.sd.constants_map().items())
              if imp._trainable(n, arr)]
    if frozen:
        import warnings
        warnings.warn(
            f"control-flow function {fname!r} contains weight constants "
            f"{frozen[:3]}{'...' if len(frozen) > 3 else ''} that match "
            f"the trainable predicate; weights inside While/If bodies "
            f"import as FROZEN constants (hoist them out of the "
            f"function, or train outer parameters only)",
            stacklevel=2)
    outs = []
    for oa in fd.output_args:
        ref = fd.ret.get(oa.name, oa.name)
        outs.append(sub._materialize(sub._resolve(ref)).name)
    return cf.subgraph_to_json(sub.sd, [a.name for a in fd.input_args], outs)


@_mapper("StatelessWhile", "While")
def _m_while(imp, node, ins):
    cond_g = _import_function_body(imp, node.attr("cond").func)
    body_g = _import_function_body(imp, node.attr("body").func)
    vars_ = [imp._materialize(v) for v in ins]
    outs = imp.sd.invoke("while_loop", vars_,
                         {"cond_graph": cond_g, "body_graph": body_g,
                          "n_loop": len(vars_)},
                         name=node.name, n_outputs=len(vars_))
    outs = outs if isinstance(outs, list) else [outs]
    return [_Val(var=o) for o in outs]


@_mapper("StatelessIf", "If")
def _m_if(imp, node, ins):
    tg = _import_function_body(imp, node.attr("then_branch").func)
    fg = _import_function_body(imp, node.attr("else_branch").func)
    if len(tg["outputs"]) != len(fg["outputs"]):
        raise TFImportError(
            f"If node {node.name!r}: then_branch returns "
            f"{len(tg['outputs'])} outputs but else_branch returns "
            f"{len(fg['outputs'])}")
    pred = imp._materialize(ins[0])
    operands = [imp._materialize(v) for v in ins[1:]]
    outs = imp.sd.invoke("cond_branch", [pred] + operands,
                         {"true_graph": tg, "false_graph": fg},
                         name=node.name, n_outputs=len(tg["outputs"]))
    outs = outs if isinstance(outs, list) else [outs]
    return [_Val(var=o) for o in outs]


# ---------------------------------------------------------------------------
def import_tf_graph(source: Union[str, bytes, GraphDef],
                    trainable: Union[None, str, Callable] = None,
                    input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                    ) -> SameDiff:
    """Import a frozen TF GraphDef (.pb path, bytes, or decoded GraphDef)
    into a runnable SameDiff graph.

    Reference: TFGraphMapper.importGraph (TFGraphMapper.java:56) /
    ImportGraph.importGraph (ImportGraph.kt:218).

    trainable: None (all consts stay CONSTANT — inference),
      "auto" (float consts of rank>=1 become trainable VARIABLEs), or a
      predicate ``fn(node_name, np_array) -> bool``.
    input_shapes: overrides for placeholder shapes (concrete batch dims
      let Shape-derived reshapes fold statically).
    """
    if isinstance(source, (str, bytes)):
        graph = GraphDef.from_file(source) if isinstance(source, str) \
            else GraphDef(source)
    else:
        graph = source
    return TFImporter(graph, trainable=trainable,
                      input_shapes=input_shapes).run()


def supported_tf_ops() -> List[str]:
    """All mapped NodeDef op names (plus Const/Placeholder/NoOp handled
    inline) — the coverage ledger for the importer."""
    return sorted(set(_MAPPERS) | {"Const", "Placeholder",
                                   "PlaceholderWithDefault", "NoOp"})
