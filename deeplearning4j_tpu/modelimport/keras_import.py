"""Keras .h5 model import.

Reference parity: deeplearning4j-modelimport —
KerasModelImport.java:45,83 → KerasModel.java:61 / KerasSequentialModel,
per-layer KerasLayer mappers (keras/layers/*, 61 classes), weights copied
from the HDF5 archive (Hdf5Archive.java:43). Here: h5py reads the archive,
~20 core Keras layer types map onto the existing config DSL, and weights
copy into the built SameDiff graph by the layer API's deterministic
parameter names.

Layout policy (same as the reference): Keras channels_last models import
into this framework's NCHW convention — callers feed NCHW inputs
(transpose of the Keras NHWC input). Flatten-then-Dense kernels are
row-permuted from HWC to CHW flat order exactly like the reference's
KerasFlatten preprocessor handling.

Supports the Keras "legacy H5" format written by tf.keras model.save
(Keras 2 `batch_input_shape` and Keras 3 `batch_shape` configs).
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


# ----------------------------------------------------------------------
# HDF5 archive (reference: keras/Hdf5Archive.java:43)
class _H5Archive:
    def __init__(self, path):
        import h5py
        self._f = h5py.File(path, "r")

    def model_config(self) -> dict:
        raw = self._f.attrs["model_config"]
        if isinstance(raw, bytes):
            raw = raw.decode()
        return json.loads(raw)

    def layer_weights(self, layer_name: str) -> List[np.ndarray]:
        """Weights for one layer in Keras weight_names order."""
        mw = self._f["model_weights"]
        if layer_name not in mw:
            return []
        g = mw[layer_name]
        names = [n.decode() if isinstance(n, bytes) else n
                 for n in g.attrs.get("weight_names", [])]
        out = []
        for n in names:
            # weight paths are rooted at model_weights, not the layer group
            node = mw[n] if n in mw else g[n]
            out.append(np.asarray(node))
        return out

    def close(self):
        self._f.close()


# ----------------------------------------------------------------------
def _input_type_from_shape(shape):
    """Keras batch shape → InputType (NHWC → NCHW convention flip)."""
    from deeplearning4j_tpu.nn import InputType
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:          # (T, C) sequence
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:          # (H, W, C) image
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 4:          # (D|T, H, W, C) volume / image sequence
        return InputType.convolutional3d(dims[0], dims[1], dims[2], dims[3])
    raise ValueError(f"unsupported Keras input shape {shape}")


def _act(name) -> str:
    if name in (None, "linear"):
        return "identity"
    if isinstance(name, dict):
        name = name.get("class_name", "linear").lower()
    return name


def _pad(cfg) -> str:
    """Keras padding mode → convolution_mode. CAUSAL (Conv1D-only in
    Keras) is not supported — reject with a descriptive error rather
    than a raw KeyError."""
    mode = cfg.get("padding", "valid")
    table = {"valid": "VALID", "same": "SAME"}
    if mode not in table:
        raise ValueError(
            f"Keras padding={mode!r} is not supported by import "
            f"(supported: {sorted(table)})")
    return table[mode]


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _flat_pairs3d(v):
    """Keras 3D pad/crop spec (int | 3 ints | 3 pairs) -> flat 6-tuple
    (d0, d1, h0, h1, w0, w1)."""
    if isinstance(v, int):
        v = ((v, v),) * 3
    flat = []
    for q in v:
        a, b = (q, q) if isinstance(q, int) else q
        flat += [int(a), int(b)]
    return tuple(flat)


class _Ctx:
    """Carries cross-layer import state (pending Flatten permutation)."""

    def __init__(self):
        # (h, w, c) recorded when Flatten consumed spatial input; the next
        # Dense kernel's rows get permuted HWC→CHW (only when the built
        # network runs the NCHW layout internally)
        self.flatten_hwc: Optional[Tuple[int, int, int]] = None
        # cnn_data_format of the configuration actually built — set by the
        # import driver after .build(); weight setters read it
        self.cnn_format: Optional[str] = None


def _reject_unsupported(cfg: dict, layer_cls: str, checks: Dict[str, object]):
    """Raise on semantically significant config this import cannot honor
    (reference: UnsupportedKerasConfigurationException) — silent drops
    would import a model whose outputs diverge from Keras."""
    for key, allowed in checks.items():
        val = cfg.get(key, allowed if not isinstance(allowed, tuple)
                      else allowed[0])
        ok = val in allowed if isinstance(allowed, tuple) else val == allowed
        if not ok:
            raise ValueError(
                f"Keras {layer_cls} config {key}={val!r} is not supported "
                f"by import (supported: {allowed!r})")


# each mapper: (keras_cfg, ctx, itype) -> (layer | None, weight_setter)
# weight_setter: (sd, lname_stem, keras_weights) -> None
def _set_simple(wmap: Dict[str, int]):
    """Setter assigning keras weights[i] to param '<stem>_<suffix>'."""
    def setter(sd, stem, weights):
        for suffix, i in wmap.items():
            if i < len(weights):
                _assign(sd, f"{stem}_{suffix}", weights[i])
    return setter


def _assign(sd, name, value):
    import jax.numpy as jnp
    if name not in sd._vars:
        raise ValueError(f"import: no parameter {name!r} in built graph")
    expect = sd._arrays[name].shape
    if tuple(value.shape) != tuple(expect):
        raise ValueError(f"import: {name} shape {value.shape} != {expect}")
    sd._arrays[name] = jnp.asarray(value, sd._arrays[name].dtype)


def _map_dense(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import DenseLayer
    layer = DenseLayer(n_out=cfg["units"], activation=_act(cfg["activation"]),
                       has_bias=cfg.get("use_bias", True))
    flat = ctx.flatten_hwc
    ctx.flatten_hwc = None

    def setter(sd, stem, weights):
        w = weights[0]
        if flat is not None and (ctx.cnn_format or "NHWC") == "NCHW":
            # NCHW runtime flatten order is CHW; Keras kernels are HWC —
            # permute rows. The NHWC runtime (default) flattens HWC
            # already, exactly matching the Keras kernel row order.
            h, wd, c = flat
            w = (w.reshape(h, wd, c, -1).transpose(2, 0, 1, 3)
                 .reshape(h * wd * c, -1))
        _assign(sd, f"{stem}_W", w)
        if len(weights) > 1:
            _assign(sd, f"{stem}_b", weights[1])
    return layer, setter


def _map_conv2d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ConvolutionLayer
    _reject_unsupported(cfg, "Conv2D", {"data_format": "channels_last",
                                        "groups": 1})
    layer = ConvolutionLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    return layer, _set_simple({"W": 0, "b": 1})


def _map_conv1d(cfg, ctx, itype):
    _reject_unsupported(cfg, "Conv1D", {"data_format": "channels_last"})
    from deeplearning4j_tpu.nn import Convolution1DLayer
    layer = Convolution1DLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"])[0],
        stride=_pair(cfg.get("strides", 1))[0], convolution_mode=_pad(cfg),
        dilation=_pair(cfg.get("dilation_rate", 1))[0],
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    return layer, _set_simple({"W": 0, "b": 1})


def _map_depthwise(cfg, ctx, itype):
    _reject_unsupported(cfg, "DepthwiseConv2D", {"data_format": "channels_last"})
    from deeplearning4j_tpu.nn import DepthwiseConvolution2DLayer
    layer = DepthwiseConvolution2DLayer(
        depth_multiplier=cfg.get("depth_multiplier", 1),
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    return layer, _set_simple({"W": 0, "b": 1})


def _map_separable(cfg, ctx, itype):
    _reject_unsupported(cfg, "SeparableConv2D", {"data_format": "channels_last"})
    from deeplearning4j_tpu.nn import SeparableConvolution2DLayer
    layer = SeparableConvolution2DLayer(
        n_out=cfg["filters"], depth_multiplier=cfg.get("depth_multiplier", 1),
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    # keras order: depthwise_kernel, pointwise_kernel, bias
    return layer, _set_simple({"dW": 0, "pW": 1, "b": 2})


def _map_conv2d_transpose(cfg, ctx, itype):
    _reject_unsupported(cfg, "Conv2DTranspose", {"data_format": "channels_last"})
    from deeplearning4j_tpu.nn import Deconvolution2DLayer
    layer = Deconvolution2DLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    # keras kernel (kh, kw, out, in) == this framework's deconv layout
    return layer, _set_simple({"W": 0, "b": 1})


def _map_pool(pool_type):
    def mapper(cfg, ctx, itype):
        from deeplearning4j_tpu.nn import SubsamplingLayer
        layer = SubsamplingLayer(
            pooling_type=pool_type, kernel_size=_pair(cfg["pool_size"]),
            stride=_pair(cfg.get("strides") or cfg["pool_size"]),
            convolution_mode=_pad(cfg))
        return layer, None
    return mapper


def _map_global_pool(pool_type):
    def mapper(cfg, ctx, itype):
        from deeplearning4j_tpu.nn import GlobalPoolingLayer
        return GlobalPoolingLayer(pooling_type=pool_type), None
    return mapper


def _map_conv3d(cfg, ctx, itype):
    _reject_unsupported(cfg, "Conv3D", {"data_format": "channels_last",
                                        "groups": 1})
    from deeplearning4j_tpu.nn import Convolution3DLayer
    layer = Convolution3DLayer(
        n_out=cfg["filters"], kernel_size=_triple(cfg["kernel_size"]),
        stride=_triple(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        dilation=_triple(cfg.get("dilation_rate", 1)),
        activation=_act(cfg["activation"]),
        has_bias=cfg.get("use_bias", True))
    # keras conv3d kernel (kd, kh, kw, cin, cout) == this layout exactly
    return layer, _set_simple({"W": 0, "b": 1})


def _map_pool3d(pool_type):
    def mapper(cfg, ctx, itype):
        from deeplearning4j_tpu.nn import Subsampling3DLayer
        layer = Subsampling3DLayer(
            pooling_type=pool_type, kernel_size=_triple(cfg["pool_size"]),
            stride=_triple(cfg.get("strides") or cfg["pool_size"]),
            convolution_mode=_pad(cfg))
        return layer, None
    return mapper


def _map_upsampling3d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Upsampling3DLayer
    return Upsampling3DLayer(size=_triple(cfg.get("size", 2))), None


def _map_zeropad3d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ZeroPadding3DLayer
    return ZeroPadding3DLayer(padding=_flat_pairs3d(cfg["padding"])), None


def _map_conv_lstm2d(cfg, ctx, itype):
    _reject_unsupported(cfg, "ConvLSTM2D", {
        "data_format": "channels_last", "activation": "tanh",
        "recurrent_activation": "sigmoid", "go_backwards": False,
        "use_bias": True,
        "dilation_rate": (1, [1, 1], (1, 1), [1], (1,))})
    from deeplearning4j_tpu.nn.recurrent_layers import ConvLSTM2DLayer
    layer = ConvLSTM2DLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=_pad(cfg),
        return_sequences=cfg.get("return_sequences", False))
    # keras: [kernel (kh,kw,cin,4F), recurrent_kernel (kh,kw,F,4F),
    # bias (4F,)]; gate order i,f,c,o == conv_lstm2d's i,f,g,o
    return layer, _set_simple({"Wih": 0, "Whh": 1, "b": 2})


def _map_gaussian_noise(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import GaussianNoiseLayer
    return GaussianNoiseLayer(stddev=cfg.get("stddev", 0.1)), None


def _map_gaussian_dropout(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import GaussianDropoutLayer
    return GaussianDropoutLayer(rate=cfg.get("rate", 0.1)), None


def _map_alpha_dropout(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import AlphaDropoutLayer
    # keras rate = DROP probability; the layer takes retain probability
    return AlphaDropoutLayer(dropout=1.0 - cfg.get("rate", 0.05)), None


def _map_spatial_dropout(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import SpatialDropoutLayer
    return SpatialDropoutLayer(dropout=1.0 - cfg.get("rate", 0.1)), None


def _map_softmax_layer(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ActivationLayer
    axis = cfg.get("axis", -1)
    if axis not in (-1, len(getattr(itype, "dims", (0,)))):
        raise ValueError(f"Keras Softmax axis={axis} is not the feature "
                         f"axis; unsupported by import")
    return ActivationLayer(activation="softmax"), None


def _map_thresholded_relu(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ActivationLayer
    # the activation resolver carries no theta attr; only the op default
    # (theta=1.0) imports — reject anything else loudly
    theta = cfg.get("theta", 1.0)
    if theta != 1.0:
        raise ValueError("Keras ThresholdedReLU theta != 1.0 is not "
                         "supported by import")
    return ActivationLayer(activation="thresholdedrelu"), None


def _map_cropping3d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Cropping3DLayer
    return Cropping3DLayer(cropping=_flat_pairs3d(cfg["cropping"])), None


def _map_batchnorm(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import BatchNormalization
    layer = BatchNormalization(decay=cfg.get("momentum", 0.99),
                               eps=cfg.get("epsilon", 1e-3))
    scale = cfg.get("scale", True)
    center = cfg.get("center", True)

    def setter(sd, stem, weights):
        i = 0
        if scale:
            _assign(sd, f"{stem}_gamma", weights[i]); i += 1
        if center:
            _assign(sd, f"{stem}_beta", weights[i]); i += 1
        _assign(sd, f"{stem}_mean", weights[i]); i += 1
        _assign(sd, f"{stem}_var", weights[i])
    return layer, setter


def _map_dropout(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import DropoutLayer
    # keras rate = drop prob; this framework uses retain prob
    return DropoutLayer(dropout=1.0 - cfg["rate"]), None


def _map_activation(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ActivationLayer
    return ActivationLayer(activation=_act(cfg["activation"])), None


def _map_flatten(cfg, ctx, itype):
    # no layer: the cnn→ff preprocessor emits the reshape; record the HWC
    # permutation for the next Dense (reference: KerasFlatten)
    if itype.kind == "cnn":
        c, h, w = itype.dims
        ctx.flatten_hwc = (h, w, c)
    return None, None


def _map_embedding(cfg, ctx, itype):
    from deeplearning4j_tpu.nn.attention import EmbeddingSequenceLayer
    layer = EmbeddingSequenceLayer(n_in=cfg["input_dim"],
                                   n_out=cfg["output_dim"])
    return layer, _set_simple({"W": 0})


def _map_lstm(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import LSTMLayer
    _reject_unsupported(cfg, "LSTM", {
        "activation": "tanh", "recurrent_activation": "sigmoid",
        "go_backwards": False, "use_bias": True})
    layer = LSTMLayer(n_out=cfg["units"],
                      return_sequences=cfg.get("return_sequences", False))
    # keras gate order [i, f, c, o] == lstm_cell's [i, f, g, o]
    return layer, _set_simple({"Wih": 0, "Whh": 1, "b": 2})


def _map_simple_rnn(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import SimpleRnnLayer
    _reject_unsupported(cfg, "SimpleRNN", {"go_backwards": False,
                                           "use_bias": True})
    layer = SimpleRnnLayer(n_out=cfg["units"],
                           activation=_act(cfg.get("activation", "tanh")),
                           return_sequences=cfg.get("return_sequences",
                                                    False))
    return layer, _set_simple({"W": 0, "U": 1, "b": 2})


def _map_bidirectional(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Bidirectional
    inner_cfg = cfg["layer"]
    inner_cls = inner_cfg["class_name"]
    inner_map = _MAPPERS[inner_cls]
    inner_layer, inner_setter = inner_map(inner_cfg["config"], ctx, itype)
    merge = {"concat": "CONCAT", "sum": "ADD", "ave": "AVERAGE",
             "mul": "MUL"}[cfg.get("merge_mode", "concat")]
    layer = Bidirectional(layer=inner_layer, mode=merge)

    def setter(sd, stem, weights):
        half = len(weights) // 2
        inner_setter(sd, f"{stem}_fwd", weights[:half])
        inner_setter(sd, f"{stem}_bwd", weights[half:])
    return layer, setter


def _map_zeropad(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ZeroPaddingLayer
    p = cfg["padding"]
    if isinstance(p, int):
        pad = (p, p, p, p)
    else:
        (t, b), (l, r) = p
        pad = (t, b, l, r)
    return ZeroPaddingLayer(padding=pad), None


def _map_cropping(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Cropping2DLayer
    cr = cfg["cropping"]
    if isinstance(cr, int):
        crop = (cr, cr, cr, cr)
    else:
        (t, b), (l, r) = cr
        crop = (t, b, l, r)
    return Cropping2DLayer(cropping=crop), None


def _map_upsampling(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Upsampling2DLayer
    return Upsampling2DLayer(size=_pair(cfg["size"])), None




def _map_gru(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import GRULayer
    _reject_unsupported(cfg, "GRU", {
        "activation": "tanh", "recurrent_activation": "sigmoid",
        "go_backwards": False, "use_bias": True, "reset_after": True})
    layer = GRULayer(n_out=cfg["units"],
                     return_sequences=cfg.get("return_sequences", False))

    def reorder(w):
        # keras gate order [z, r, h] -> gru_cell's [r, z, h]
        z, r, h = np.split(w, 3, axis=-1)
        return np.concatenate([r, z, h], axis=-1)

    def setter(sd, stem, weights):
        _assign(sd, f"{stem}_Wih", reorder(weights[0]))
        _assign(sd, f"{stem}_Whh", reorder(weights[1]))
        # reset_after=True: bias (2, 3u) = [input bias; recurrent bias]
        b = weights[2]
        _assign(sd, f"{stem}_bih", reorder(b[0]))
        _assign(sd, f"{stem}_bhh", reorder(b[1]))
    return layer, setter


def _map_layer_norm(cfg, ctx, itype):
    from deeplearning4j_tpu.nn.attention import LayerNormLayer
    ax = cfg.get("axis", -1)
    ax = ax[0] if isinstance(ax, (list, tuple)) else ax
    if ax not in (-1, len(itype.dims)):
        raise ValueError(f"Keras LayerNormalization axis={ax} unsupported "
                         f"(feature-axis only)")
    layer = LayerNormLayer(eps=cfg.get("epsilon", 1e-3))
    scale = cfg.get("scale", True)
    center = cfg.get("center", True)

    def setter(sd, stem, weights):
        # keras saves only the enabled params, in [gamma, beta] order
        i = 0
        if scale:
            _assign(sd, f"{stem}_g", weights[i]); i += 1
        if center:
            _assign(sd, f"{stem}_b", weights[i])
    setter.allow_empty = not (scale or center)
    return layer, setter


def _map_prelu(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import PReLULayer
    layer = PReLULayer()

    def setter(sd, stem, weights):
        name = f"{stem}_alpha"
        expect = sd._arrays[name].shape
        _assign(sd, name, np.asarray(weights[0]).reshape(expect))
    return layer, setter


def _map_leaky_relu(cfg, ctx, itype):
    # PReLU with every alpha fixed to the keras slope (keras default 0.3
    # vs the framework activation's 0.01 — a plain activation would
    # silently change the slope)
    from deeplearning4j_tpu.nn import PReLULayer
    alpha = cfg.get("alpha", cfg.get("negative_slope", 0.3))
    layer = PReLULayer()

    def setter(sd, stem, weights):
        name = f"{stem}_alpha"
        expect = sd._arrays[name].shape
        _assign(sd, name, np.full(expect, float(alpha), np.float32))
        # keras LeakyReLU's slope is a CONSTANT, not a parameter — freeze
        # it so fine-tuning cannot drift the activation
        sd.convert_to_constant(sd.get_variable(name))
    setter.allow_empty = True    # the slope is config, not a keras weight
    return layer, setter


def _map_elu(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ActivationLayer
    if cfg.get("alpha", 1.0) != 1.0:
        raise ValueError("Keras ELU alpha != 1.0 unsupported")
    return ActivationLayer(activation="elu"), None


def _map_reshape(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ReshapeLayer
    return ReshapeLayer(target_shape=tuple(cfg["target_shape"])), None


def _map_permute(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import PermuteLayer
    return PermuteLayer(dims=tuple(cfg["dims"])), None


def _map_repeat_vector(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import RepeatVectorLayer
    return RepeatVectorLayer(n=cfg["n"]), None


def _map_time_distributed(cfg, ctx, itype):
    inner = cfg["layer"]
    if inner["class_name"] != "Dense":
        raise ValueError("TimeDistributed import supports Dense only "
                         f"(got {inner['class_name']})")
    # DenseLayer broadcasts over (B, T, C) already
    return _map_dense(inner["config"], ctx, itype)


def _map_pool1d(pool_type):
    def mapper(cfg, ctx, itype):
        from deeplearning4j_tpu.nn import Subsampling1DLayer
        ps = cfg["pool_size"]
        ps = ps[0] if isinstance(ps, (list, tuple)) else ps
        st = cfg.get("strides") or ps
        st = st[0] if isinstance(st, (list, tuple)) else st
        return Subsampling1DLayer(pooling_type=pool_type, kernel_size=ps,
                                  stride=st,
                                  convolution_mode=_pad(cfg)), None
    return mapper


def _map_zeropad1d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import ZeroPadding1DLayer
    p = cfg["padding"]
    pad = (p, p) if isinstance(p, int) else tuple(p)
    return ZeroPadding1DLayer(padding=pad), None


def _map_cropping1d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Cropping1DLayer
    c = cfg["cropping"]
    crop = (c, c) if isinstance(c, int) else tuple(c)
    return Cropping1DLayer(cropping=crop), None


def _map_upsampling1d(cfg, ctx, itype):
    from deeplearning4j_tpu.nn import Upsampling1DLayer
    return Upsampling1DLayer(size=cfg.get("size", 2)), None


def _map_mha(cfg, ctx, itype):
    from deeplearning4j_tpu.nn.attention import MultiHeadAttentionLayer
    h = cfg["num_heads"]
    dk = cfg["key_dim"]
    if cfg.get("value_dim") not in (None, dk):
        raise ValueError(f"Keras MultiHeadAttention value_dim="
                         f"{cfg['value_dim']!r} != key_dim {dk} is not "
                         f"supported by import")
    out_shape = cfg.get("output_shape")
    if isinstance(out_shape, (list, tuple)):
        if len(out_shape) != 1:
            raise ValueError(f"Keras MultiHeadAttention output_shape="
                             f"{out_shape!r} unsupported (rank-1 only)")
        out_shape = out_shape[0]
    use_bias = cfg.get("use_bias", True)
    layer = MultiHeadAttentionLayer(n_heads=h, head_size=dk,
                                    n_out=out_shape or 0,
                                    has_bias=use_bias)

    def setter(sd, stem, weights):
        # keras order with use_bias: q/kernel (d,H,dk), q/bias (H,dk),
        # k/kernel, k/bias, v/kernel, v/bias, out/kernel (H,dk,d_out),
        # out/bias (d_out,); without bias the 4 kernels only
        d = weights[0].shape[0]
        step = 2 if use_bias else 1
        _assign(sd, f"{stem}_Wq", weights[0].reshape(d, h * dk))
        _assign(sd, f"{stem}_Wk", weights[step].reshape(d, h * dk))
        _assign(sd, f"{stem}_Wv", weights[2 * step].reshape(d, h * dk))
        wo = weights[3 * step]
        d_out = wo.shape[-1]
        _assign(sd, f"{stem}_Wo", wo.reshape(h * dk, d_out))
        if use_bias:
            _assign(sd, f"{stem}_bq", weights[1].reshape(h * dk))
            _assign(sd, f"{stem}_bk", weights[3].reshape(h * dk))
            _assign(sd, f"{stem}_bv", weights[5].reshape(h * dk))
            _assign(sd, f"{stem}_bo", weights[7].reshape(d_out))
    return layer, setter


_MAPPERS: Dict[str, Callable] = {
    "Dense": _map_dense,
    "Conv2D": _map_conv2d,
    "Conv1D": _map_conv1d,
    "DepthwiseConv2D": _map_depthwise,
    "SeparableConv2D": _map_separable,
    "Conv2DTranspose": _map_conv2d_transpose,
    "MaxPooling2D": _map_pool("MAX"),
    "AveragePooling2D": _map_pool("AVG"),
    "GlobalAveragePooling2D": _map_global_pool("AVG"),
    "GlobalMaxPooling2D": _map_global_pool("MAX"),
    "GlobalAveragePooling1D": _map_global_pool("AVG"),
    "GlobalMaxPooling1D": _map_global_pool("MAX"),
    "BatchNormalization": _map_batchnorm,
    "Dropout": _map_dropout,
    "Activation": _map_activation,
    "Flatten": _map_flatten,
    "Embedding": _map_embedding,
    "LSTM": _map_lstm,
    "SimpleRNN": _map_simple_rnn,
    "Bidirectional": _map_bidirectional,
    "ZeroPadding2D": _map_zeropad,
    "Cropping2D": _map_cropping,
    "UpSampling2D": _map_upsampling,
    "GRU": _map_gru,
    "LayerNormalization": _map_layer_norm,
    "PReLU": _map_prelu,
    "LeakyReLU": _map_leaky_relu,
    "ELU": _map_elu,
    "Reshape": _map_reshape,
    "Permute": _map_permute,
    "RepeatVector": _map_repeat_vector,
    "TimeDistributed": _map_time_distributed,
    "MaxPooling1D": _map_pool1d("MAX"),
    "AveragePooling1D": _map_pool1d("AVG"),
    "ZeroPadding1D": _map_zeropad1d,
    "Cropping1D": _map_cropping1d,
    "UpSampling1D": _map_upsampling1d,
    "MultiHeadAttention": _map_mha,
    "Conv3D": _map_conv3d,
    "MaxPooling3D": _map_pool3d("MAX"),
    "AveragePooling3D": _map_pool3d("AVG"),
    "UpSampling3D": _map_upsampling3d,
    "ZeroPadding3D": _map_zeropad3d,
    "ConvLSTM2D": _map_conv_lstm2d,
    "GaussianNoise": _map_gaussian_noise,
    "GaussianDropout": _map_gaussian_dropout,
    "AlphaDropout": _map_alpha_dropout,
    "SpatialDropout1D": _map_spatial_dropout,
    "SpatialDropout2D": _map_spatial_dropout,
    "SpatialDropout3D": _map_spatial_dropout,
    "Softmax": _map_softmax_layer,
    "ThresholdedReLU": _map_thresholded_relu,
    "Cropping3D": _map_cropping3d,
}


def _batch_shape(cfg: dict):
    return cfg.get("batch_input_shape") or cfg.get("batch_shape")


def _import_sequential(model_cfg: dict, archive: _H5Archive):
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    layers_cfg = model_cfg["config"]["layers"]
    itype = _initial_itype(layers_cfg)      # single source of input typing
    ctx = _Ctx()
    built = []               # (our_layer, keras_name, setter)
    for lc in layers_cfg:
        cls = lc["class_name"]
        cfg = lc["config"]
        if cls == "InputLayer":
            continue
        if cls not in _MAPPERS:
            raise ValueError(f"Keras layer {cls} not supported by import "
                             f"(supported: {sorted(_MAPPERS)})")
        layer, setter = _MAPPERS[cls](cfg, ctx, itype)
        if layer is not None:
            built.append((layer, cfg["name"], setter))
            itype = layer.output_type(_adapt(itype, layer))
        elif cls == "Flatten":
            itype = _flatten_itype(itype)

    b = NeuralNetConfiguration.builder().seed(0).list()
    for layer, _, _ in built:
        b = b.layer(layer)
    conf = b.set_input_type(_initial_itype(layers_cfg)).build()
    ctx.cnn_format = conf.cnn_data_format
    net = MultiLayerNetwork(conf).init()
    _copy_weights(net, built, archive)
    return net


def _initial_itype(layers_cfg):
    """Derive the model InputType: int-dtype 2D inputs and Embedding-first
    models are token ids; everything else maps by rank."""
    from deeplearning4j_tpu.nn.attention import sequence_ids
    for lc in layers_cfg:
        cfg = lc["config"]
        shape = _batch_shape(cfg)
        if shape is None:
            continue
        nxt = [l for l in layers_cfg if l["class_name"] != "InputLayer"]
        is_ids = len(shape) == 2 and (
            (nxt and nxt[0]["class_name"] == "Embedding")
            or "int" in str(cfg.get("dtype", "")))
        if is_ids:
            return sequence_ids(shape[1])
        return _input_type_from_shape(shape)
    raise ValueError("no input shape in Keras config")


def _adapt(itype, layer):
    from deeplearning4j_tpu.nn.multilayer import _adapt_itype
    return _adapt_itype(itype, layer, 0)


def _flatten_itype(itype):
    from deeplearning4j_tpu.nn import InputType
    return InputType.feed_forward(itype.flat_size) \
        if itype.kind in ("cnn", "cnn3d") else itype


def _copy_weights(net, built, archive: _H5Archive):
    """Copy Keras weights into the train graph by deterministic param
    names (layer{idx}_{kind} stems), then sync the inference graph."""
    sd = net._sd_train
    stems = _layer_stems(net)
    for idx, (layer, keras_name, setter) in enumerate(built):
        if setter is None:
            continue
        weights = archive.layer_weights(keras_name)
        if not weights and not getattr(setter, "allow_empty", False):
            raise ValueError(f"no weights for Keras layer {keras_name!r}")
        setter(sd, stems[idx], weights)
    net._sync_infer()


_KIND_STEM = {
    "DenseLayer": "dense", "ConvolutionLayer": "conv",
    "Convolution1DLayer": "conv1d", "DepthwiseConvolution2DLayer": "dwconv",
    "SeparableConvolution2DLayer": "sepconv",
    "Deconvolution2DLayer": "deconv", "BatchNormalization": "bn",
    "LSTMLayer": "lstm", "SimpleRnnLayer": "rnn", "Bidirectional": "bidir",
    "EmbeddingSequenceLayer": "embedseq", "EmbeddingLayer": "embedding",
    "GRULayer": "gru", "LayerNormLayer": "ln", "PReLULayer": "prelu",
    "MultiHeadAttentionLayer": "mha", "RepeatVectorLayer": "repeat",
    "PermuteLayer": "permute", "ReshapeLayer": "reshape",
    "Subsampling1DLayer": "pool1d", "ZeroPadding1DLayer": "zeropad1d",
    "Cropping1DLayer": "crop1d", "Upsampling1DLayer": "upsample1d",
    "GravesLSTMLayer": "glstm",
    "Convolution3DLayer": "conv3d", "Subsampling3DLayer": "pool3d",
    "Upsampling3DLayer": "upsample3d", "ZeroPadding3DLayer": "zeropad3d",
    "ConvLSTM2DLayer": "convlstm",
}


def _layer_stems(net) -> List[str]:
    """Parameter-name stem per layer index (mirrors ctx.lname)."""
    return [f"layer{i}_{_KIND_STEM.get(type(l).__name__, 'x')}"
            for i, l in enumerate(net.conf.layers)]


# ----------------------------------------------------------------------
def import_keras_sequential_model_and_weights(path):
    """Import a Sequential .h5 → MultiLayerNetwork (reference:
    KerasModelImport.importKerasSequentialModelAndWeights,
    KerasModelImport.java:83)."""
    archive = _H5Archive(path)
    try:
        cfg = archive.model_config()
        if cfg["class_name"] != "Sequential":
            raise ValueError(f"not a Sequential model: {cfg['class_name']} "
                             f"(use import_keras_model_and_weights)")
        return _import_sequential(cfg, archive)
    finally:
        archive.close()


def import_keras_model_and_weights(path):
    """Import a Sequential or functional .h5 (reference:
    KerasModelImport.importKerasModelAndWeights, KerasModelImport.java:45).
    Functional models map onto ComputationGraph."""
    archive = _H5Archive(path)
    try:
        cfg = archive.model_config()
        if cfg["class_name"] == "Sequential":
            return _import_sequential(cfg, archive)
        if cfg["class_name"] in ("Functional", "Model"):
            return _import_functional(cfg, archive)
        raise ValueError(f"unsupported Keras model class "
                         f"{cfg['class_name']}")
    finally:
        archive.close()


def _vname(name: str, call_idx: int) -> str:
    """Graph vertex name for a Keras layer call site. Shared layers
    (called k>1 times) expand into k vertices."""
    return name if call_idx == 0 else f"{name}__call{call_idx}"


def _import_functional(model_cfg: dict, archive: _H5Archive):
    """Functional API → ComputationGraph. Supports the merge vertices the
    graph API has (Add/Average/Maximum/Multiply/Subtract/Concatenate).

    Shared layers (one Keras layer called at multiple graph positions)
    expand into one vertex PER CALL SITE; every call site receives the
    same imported weights. Note the expansion un-ties the copies for
    subsequent fine-tuning — gradient updates are per-call-site (the
    reference rejects shared-layer graphs outright:
    KerasLayer.getInboundLayerNames handles a single inbound node).
    """
    from deeplearning4j_tpu.nn import (ComputationGraph, ElementWiseVertex,
                                       MergeVertex, NeuralNetConfiguration)
    cfg = model_cfg["config"]
    layers_cfg = {lc["config"]["name"]: lc for lc in cfg["layers"]}
    order = [lc["config"]["name"] for lc in cfg["layers"]]

    def inbound(lc) -> List[List[Tuple[str, int]]]:
        """Per call site: [(source layer name, source call index), ...]."""
        sites = []
        for node in lc.get("inbound_nodes", []):
            if isinstance(node, dict):   # keras 3 style
                args = node.get("args", [])
                names: List[Tuple[str, int]] = []

                def walk(a):
                    if isinstance(a, dict) and "config" in a and \
                            "keras_history" in a["config"]:
                        hist = a["config"]["keras_history"]
                        names.append((hist[0], int(hist[1])))
                    elif isinstance(a, (list, tuple)):
                        for x in a:
                            walk(x)
                walk(args)
                sites.append(names)
            else:                        # keras 2 style [[name, n, t, {}]]
                sites.append([(n[0], int(n[1])) for n in node])
        return sites

    def _names(spec) -> List[Tuple[str, int]]:
        # keras 2: [["name", node, tensor], ...]; keras 3: ["name", n, t]
        if isinstance(spec, list) and spec and isinstance(spec[0], str):
            return [(spec[0], int(spec[1]) if len(spec) > 1 else 0)]
        return [(n[0], int(n[1]) if len(n) > 1 else 0)
                if isinstance(n, list) else (n, 0) for n in spec]

    g = NeuralNetConfiguration.builder().seed(0).graph_builder()
    inputs = [n for n, _ in _names(cfg["input_layers"])]
    outputs = [_vname(n, i) for n, i in _names(cfg["output_layers"])]
    g = g.add_inputs(*inputs)
    itypes = {}
    ctx = _Ctx()
    built = {}
    input_types = []
    for name in inputs:
        shape = _batch_shape(layers_cfg[name]["config"])
        it = _input_type_from_shape(shape)
        itypes[name] = it
        input_types.append(it)
    g = g.set_input_types(*input_types)

    _MERGE = {"Add": ("ew", "Add"), "Subtract": ("ew", "Subtract"),
              "Multiply": ("ew", "Product"), "Average": ("ew", "Average"),
              "Maximum": ("ew", "Max"), "Concatenate": ("merge", None),
              "Dot": ("dot", None)}
    flat_hwc = {}            # flatten vertex name -> (h, w, c) permutation
    for name in order:
        lc = layers_cfg[name]
        cls = lc["class_name"]
        if cls == "InputLayer":
            continue
        for ci, site in enumerate(inbound(lc)):
            vname = _vname(name, ci)
            srcs = [_vname(s, si) for s, si in site]
            src_itype = itypes[srcs[0]]
            if cls in _MERGE:
                kind, op = _MERGE[cls]
                in_types = [itypes[s] for s in srcs]
                # A spatial Flatten feeding a merge cannot be rewired to
                # its source: channel-concat of 4D maps is a different
                # element order than concat of HWC-flattened vectors, and
                # the downstream Dense kernel permutation is per-branch.
                # Reject loudly; no-op flattens resolve fine.
                for s in srcs:
                    if s in flat_hwc:
                        raise ValueError(
                            f"Keras {cls} {name!r} consumes Flatten {s!r} "
                            f"of a spatial tensor — Flatten-before-merge "
                            f"topologies are not supported by import")
                if kind == "dot":
                    from deeplearning4j_tpu.nn import DotProductVertex
                    axes = lc["config"].get("axes", -1)
                    ax_list = axes if isinstance(axes, (list, tuple)) \
                        else [axes, axes]
                    if any(a not in (-1, len(in_types[0].dims))
                           for a in ax_list):
                        raise ValueError(
                            f"Keras Dot axes={axes!r}: only the feature "
                            f"axis is supported by import")
                    vertex = DotProductVertex(
                        normalize=lc["config"].get("normalize", False))
                elif kind == "ew":
                    vertex = ElementWiseVertex(op=op)
                else:
                    vertex = MergeVertex()
                g = g.add_vertex(vname, vertex,
                                 *[_resolve_alias(built, s) for s in srcs])
                itypes[vname] = vertex.output_type(in_types)
                continue
            if cls not in _MAPPERS:
                raise ValueError(f"Keras layer {cls} not supported by "
                                 f"import")
            # per-branch Flatten permutation: a Dense consuming a flatten
            # alias permutes with THAT branch's spatial dims
            ctx.flatten_hwc = flat_hwc.get(srcs[0])
            layer, setter = _MAPPERS[cls](lc["config"], ctx, src_itype)
            ctx.flatten_hwc = None
            if layer is None:            # Flatten: alias to its source
                itypes[vname] = _flatten_itype(src_itype)
                if src_itype.kind == "cnn":
                    c, h, w = src_itype.dims
                    flat_hwc[vname] = (h, w, c)
                built[vname] = ("alias", srcs[0], None, name)
                continue
            g = g.add_layer(vname, layer, *[_resolve_alias(built, s)
                                            for s in srcs])
            itypes[vname] = layer.output_type(_adapt(src_itype, layer))
            built[vname] = ("layer", layer, setter, name)
    g = g.set_outputs(*[_resolve_alias(built, o) for o in outputs])
    gconf = g.build()
    ctx.cnn_format = gconf.cnn_data_format
    net = ComputationGraph(gconf).init()
    sd = net._sd_train
    for vname, entry in built.items():
        if entry[0] == "layer" and entry[2] is not None:
            weights = archive.layer_weights(entry[3])
            if not weights:
                raise ValueError(f"no weights for Keras layer "
                                 f"{entry[3]!r}")
            entry[2](sd, vname, weights)  # graph builds: stem = vertex name
    net._sync_infer()
    return net


def _resolve_alias(built, name):
    while name in built and built[name][0] == "alias":
        name = built[name][1]
    return name


class KerasModelImport:
    """Static facade matching the reference entry points
    (KerasModelImport.java:45,83)."""
    import_keras_model_and_weights = staticmethod(
        import_keras_model_and_weights)
    import_keras_sequential_model_and_weights = staticmethod(
        import_keras_sequential_model_and_weights)
