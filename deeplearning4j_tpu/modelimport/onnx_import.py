"""ONNX ModelProto → SameDiff importer.

Reference parity: nd4j samediff-import-onnx (ImportGraph.kt:218 with the
onnx OpMappingRegistry; the per-op rule table role of
ImportClassMapping.java:40). Same TPU-native design as the TF importer
(tf_import.py): structural tensors const-fold at import time into static
op attrs so the traced graph is pure dataflow; constant-propagation folds
all-const subgraphs; ``trainable="auto"`` turns float initializers of
rank>=1 into trainable VARIABLEs for fine-tuning.

ONNX specifics vs TF: graphs are topologically sorted by spec (kept as a
fallback check), weights live in graph.initializer, convs/pools are
NCHW/OIHW (kernels transpose to HWIO at import; conv ops run with
data_format="NCHW" to preserve graph semantics), and opset>=10 ops pass
structural args (Slice starts/ends, Pad pads, Clip min/max) as inputs —
all folded.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.modelimport.onnx_pb import (
    OnnxModel, onnx_dtype_to_np)
from deeplearning4j_tpu.modelimport.tf_import import TFImportError, _Val
from deeplearning4j_tpu.ops import registry


class OnnxImportError(TFImportError):
    pass


class OnnxImporter:
    def __init__(self, model: OnnxModel,
                 trainable: Union[None, str, Callable] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None):
        self.graph = model.graph
        self.sd = SameDiff()
        self.input_shapes = dict(input_shapes or {})
        self._tensors: Dict[str, _Val] = {}
        if trainable == "auto":
            self._trainable = lambda name, arr: (
                np.issubdtype(arr.dtype, np.floating) and arr.ndim >= 1)
        elif callable(trainable):
            self._trainable = trainable
        else:
            self._trainable = lambda name, arr: False
        self.placeholder_names: List[str] = []
        self.variable_names: List[str] = []

    # ------------------------------------------------------------------
    def run(self) -> SameDiff:
        g = self.graph
        for name, arr in g.initializers.items():
            if self._trainable(name, arr):
                v = self.sd.var(name, value=arr, dtype=str(arr.dtype))
                self.variable_names.append(v.name)
                self._tensors[name] = _Val(var=v)
            else:
                self._tensors[name] = _Val(const=arr, name=name)
        for name, dtype_enum, dims in g.inputs:
            if name in self._tensors:        # initializer doubles as input
                continue
            shape = self.input_shapes.get(name)
            if shape is None and dims is not None:
                shape = [(-1 if d < 0 else d) for d in dims]
            np_dt = onnx_dtype_to_np(dtype_enum) if dtype_enum \
                else np.dtype(np.float32)
            ph = self.sd.placeholder(name, shape=shape, dtype=str(np_dt))
            self.placeholder_names.append(ph.name)
            self._tensors[name] = _Val(var=ph)
        for node in g.nodes:
            try:
                self._import_node(node)
            except OnnxImportError:
                raise
            except Exception as e:
                raise OnnxImportError(
                    f"while importing node {node.op_type} "
                    f"{node.name!r}: {e}") from e
        return self.sd

    # ------------------------------------------------------------------
    def _resolve(self, ref: str) -> _Val:
        try:
            return self._tensors[ref]
        except KeyError:
            raise OnnxImportError(
                f"input {ref!r} not produced by any imported node (ONNX "
                f"graphs must be topologically sorted)") from None

    def _ins(self, node) -> List[_Val]:
        # optional inputs are empty strings in ONNX
        return [self._resolve(r) for r in node.inputs if r]

    def _materialize(self, v: _Val):
        if v.var is None:
            v.var = self.sd.constant(np.asarray(v.const),
                                     name=v._name or "onnx_const")
        return v.var

    def _const_np(self, v: _Val, what: str) -> np.ndarray:
        if not v.is_const:
            raise OnnxImportError(
                f"{what} must be trace-time constant (derived from "
                f"initializers and static shapes)")
        return np.asarray(v.const)

    def _ints(self, v, what):
        return tuple(int(x) for x in self._const_np(v, what).reshape(-1))

    def emit(self, op_name: str, ins: Sequence[_Val], attrs: Dict,
             name: str, n_outputs: int = 1) -> List[_Val]:
        if all(v.is_const for v in ins):
            fn = registry.get_op(op_name).fn
            res = fn(*[np.asarray(v.const) for v in ins], **attrs)
            res = res if isinstance(res, (tuple, list)) else [res]
            return [_Val(const=np.asarray(r), name=name) for r in res]
        vars_ = [self._materialize(v) for v in ins]
        out = self.sd.invoke(op_name, vars_, attrs=attrs, name=name,
                             n_outputs=n_outputs)
        outs = out if isinstance(out, list) else [out]
        return [_Val(var=o) for o in outs]

    def _static_shape(self, v: _Val, what: str):
        if v.is_const:
            return tuple(np.asarray(v.const).shape)
        shape = v.var.shape
        if shape is None or any(d is None or d < 0 for d in shape):
            raise OnnxImportError(f"{what}: input shape {shape} not static; "
                                  f"pass input_shapes= with concrete dims")
        return tuple(shape)

    # ------------------------------------------------------------------
    def _import_node(self, node):
        mapper = _MAPPERS.get(node.op_type)
        if mapper is None:
            raise OnnxImportError(
                f"unmapped ONNX op {node.op_type!r} (node {node.name!r}); "
                f"{len(_MAPPERS)} ops supported")
        outs = mapper(self, node, self._ins(node))
        if isinstance(outs, _Val):
            outs = [outs]
        for ref, val in zip(node.outputs, outs):
            if ref:
                val._name = val._name or ref
                self._tensors[ref] = val
                if val.var is not None and self.sd.has_variable(val.var.name) \
                        and val.var.name != ref and not self.sd.has_variable(ref):
                    self.sd.rename_variable(val.var.name, ref)


# ---------------------------------------------------------------------------
_MAPPERS: Dict[str, Callable] = {}


def _mapper(*names):
    def deco(fn):
        for n in names:
            _MAPPERS[n] = fn
        return fn
    return deco


def _a_i(node, name, default=0):
    a = node.attr(name)
    return a.i if a is not None else default


def _a_f(node, name, default=0.0):
    a = node.attr(name)
    return a.f if a is not None else default


def _a_s(node, name, default=""):
    a = node.attr(name)
    return a.s if a is not None else default


def _a_ints(node, name, default=()):
    a = node.attr(name)
    return list(a.ints) if a is not None else list(default)


def _out_name(node):
    return node.name or node.outputs[0]


# --- elementwise -----------------------------------------------------------
_UNARY = {
    "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh", "Exp": "exp",
    "Log": "log", "Sqrt": "sqrt", "Abs": "abs", "Neg": "neg",
    "Floor": "floor", "Ceil": "ceil", "Round": "round", "Erf": "erf",
    "Softplus": "softplus", "Softsign": "softsign", "Sign": "sign",
    "Reciprocal": "reciprocal", "Sin": "sin", "Cos": "cos", "Tan": "tan",
    "Asin": "asin", "Acos": "acos", "Atan": "atan", "Sinh": "sinh",
    "Cosh": "cosh", "Asinh": "asinh", "Acosh": "acosh", "Atanh": "atanh",
    "Not": "not", "Identity": "identity", "Mish": "mish",
}
for _o, _r in _UNARY.items():
    def _mk(reg):
        def m(imp, node, ins):
            return imp.emit(reg, ins, {}, _out_name(node))
        return m
    _MAPPERS[_o] = _mk(_r)

_BINARY = {
    "Add": "add", "Sub": "subtract", "Mul": "multiply", "Div": "divide",
    "Pow": "pow_pairwise", "Equal": "equals", "Greater": "greater",
    "GreaterOrEqual": "greater_equal", "Less": "less",
    "LessOrEqual": "less_equal", "And": "boolean_and", "Or": "boolean_or",
    "Xor": "boolean_xor", "Mod": "mod",
}
for _o, _r in _BINARY.items():
    def _mkb(reg):
        def m(imp, node, ins):
            return imp.emit(reg, ins, {}, _out_name(node))
        return m
    _MAPPERS[_o] = _mkb(_r)


@_mapper("Max", "Min", "Sum", "Mean")
def _m_variadic(imp, node, ins):
    reg = {"Max": "maximum", "Min": "minimum"}.get(node.op_type)
    acc = ins[0]
    for i, v in enumerate(ins[1:]):
        if reg:
            acc = imp.emit(reg, [acc, v], {}, f"{_out_name(node)}_{i}")[0]
        else:
            acc = imp.emit("add", [acc, v], {}, f"{_out_name(node)}_{i}")[0]
    if node.op_type == "Mean" and len(ins) > 1:
        acc = imp.emit("scalar_mul", [acc], {"scalar": 1.0 / len(ins)},
                       _out_name(node))[0]
    return acc


@_mapper("LeakyRelu")
def _m_leaky(imp, node, ins):
    return imp.emit("leaky_relu", ins, {"alpha": _a_f(node, "alpha", 0.01)},
                    _out_name(node))


@_mapper("Elu")
def _m_elu(imp, node, ins):
    if abs(_a_f(node, "alpha", 1.0) - 1.0) > 1e-9:
        raise OnnxImportError("Elu alpha != 1 unsupported")
    return imp.emit("elu", ins, {}, _out_name(node))


@_mapper("Selu")
def _m_selu(imp, node, ins):
    return imp.emit("selu", ins, {}, _out_name(node))


@_mapper("PRelu")
def _m_prelu(imp, node, ins):
    return imp.emit("prelu", ins, {}, _out_name(node))


@_mapper("HardSigmoid")
def _m_hard_sigmoid(imp, node, ins):
    if abs(_a_f(node, "alpha", 0.2) - 0.2) > 1e-9 or \
            abs(_a_f(node, "beta", 0.5) - 0.5) > 1e-9:
        raise OnnxImportError("HardSigmoid alpha/beta != 0.2/0.5 "
                              "unsupported")
    return imp.emit("hard_sigmoid", ins, {}, _out_name(node))


@_mapper("Softmax")
def _m_softmax(imp, node, ins):
    return imp.emit("softmax", ins, {"axis": _a_i(node, "axis", -1)},
                    _out_name(node))


@_mapper("LogSoftmax")
def _m_log_softmax(imp, node, ins):
    return imp.emit("log_softmax", ins, {"axis": _a_i(node, "axis", -1)},
                    _out_name(node))


@_mapper("Clip")
def _m_clip(imp, node, ins):
    lo = float(imp._const_np(ins[1], "Clip min")) if len(ins) > 1 \
        else float("-inf")
    hi = float(imp._const_np(ins[2], "Clip max")) if len(ins) > 2 \
        else float("inf")
    return imp.emit("clip_by_value", [ins[0]],
                    {"clip_min": lo, "clip_max": hi}, _out_name(node))


@_mapper("Where")
def _m_where(imp, node, ins):
    return imp.emit("where_op", ins, {}, _out_name(node))


@_mapper("Cast")
def _m_cast(imp, node, ins):
    dt = onnx_dtype_to_np(_a_i(node, "to", 1))
    return imp.emit("cast", ins, {"dtype": str(dt)}, _out_name(node))


@_mapper("Dropout")
def _m_dropout(imp, node, ins):
    # inference graphs: identity (mask output unsupported)
    return imp.emit("identity", [ins[0]], {}, _out_name(node))


# --- matmul / gemm ---------------------------------------------------------
@_mapper("MatMul")
def _m_matmul(imp, node, ins):
    a, b = ins
    return imp.emit("matmul", [a, b], {}, _out_name(node))


@_mapper("Gemm")
def _m_gemm(imp, node, ins):
    attrs = {"alpha": _a_f(node, "alpha", 1.0),
             "beta": _a_f(node, "beta", 1.0),
             "transpose_a": bool(_a_i(node, "transA", 0)),
             "transpose_b": bool(_a_i(node, "transB", 0))}
    mm = imp.emit("gemm", ins[:2],
                  {"alpha": attrs["alpha"],
                   "transpose_a": attrs["transpose_a"],
                   "transpose_b": attrs["transpose_b"]},
                  _out_name(node) + ("_mm" if len(ins) > 2 else ""))
    if len(ins) > 2:
        c = ins[2]
        if attrs["beta"] != 1.0:
            c = imp.emit("scalar_mul", [c], {"scalar": attrs["beta"]},
                         _out_name(node) + "_c")[0]
        return imp.emit("add", [mm[0], c], {}, _out_name(node))
    return mm


@_mapper("Einsum")
def _m_einsum(imp, node, ins):
    return imp.emit("einsum", ins, {"equation": _a_s(node, "equation")},
                    _out_name(node))


# --- conv / pool / norm (NCHW / OIHW per ONNX spec) ------------------------
def _conv_padding(node, spatial_dims=2):
    auto = _a_s(node, "auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME", None
    pads = _a_ints(node, "pads", [0] * (2 * spatial_dims))
    if any(pads):
        return "VALID", pads
    return "VALID", None


@_mapper("Conv")
def _m_conv(imp, node, ins):
    x, w = ins[0], ins[1]
    group = _a_i(node, "group", 1)
    strides = _a_ints(node, "strides", [1, 1])
    dil = _a_ints(node, "dilations", [1, 1])
    padding, pads = _conv_padding(node)
    name = _out_name(node)
    if pads:
        t, l, b, r = (pads + [0, 0, 0, 0])[:4] if len(pads) == 4 \
            else (pads[0], pads[1], pads[0], pads[1])
        x = imp.emit("pad", [x],
                     {"paddings": ((0, 0), (0, 0), (t, b), (l, r))},
                     f"{name}_pad")[0]
    # kernel OIHW -> HWIO
    w = imp.emit("permute", [w], {"axes": (2, 3, 1, 0)}, f"{name}_whwio")[0]
    if group > 1:
        c_in = None
        if ins[1].is_const:
            c_in = ins[1].const.shape[1] * group
        if c_in is None or group != c_in:
            raise OnnxImportError("grouped Conv supported only as full "
                                  "depthwise (group == C_in)")
        # depthwise: HWIO (kh, kw, 1, C) -> depthwise layout (kh, kw, C, 1)
        w = imp.emit("permute", [w], {"axes": (0, 1, 3, 2)},
                     f"{name}_wdw")[0]
        conv = imp.emit("depthwise_conv2d", [x, w] + ins[2:3], {
            "strides": tuple(strides), "padding": padding,
            "dilation": tuple(dil), "data_format": "NCHW"}, name)
        return conv
    return imp.emit("conv2d", [x, w] + ins[2:3], {
        "strides": tuple(strides), "padding": padding,
        "dilation": tuple(dil), "data_format": "NCHW"}, name)


@_mapper("ConvTranspose")
def _m_conv_transpose(imp, node, ins):
    x, w = ins[0], ins[1]
    strides = _a_ints(node, "strides", [1, 1])
    auto = _a_s(node, "auto_pad", "NOTSET")
    pads = _a_ints(node, "pads", [])
    if pads and any(pads):
        raise OnnxImportError("ConvTranspose with explicit pads "
                              "unsupported (use auto_pad)")
    name = _out_name(node)
    # ONNX deconv kernel (C_in, C_out/group, kH, kW) -> our (kh, kw, oC, iC)
    w = imp.emit("permute", [w], {"axes": (2, 3, 1, 0)}, f"{name}_w")[0]
    return imp.emit("deconv2d", [x, w] + ins[2:3], {
        "strides": tuple(strides),
        "padding": "SAME" if auto in ("SAME_UPPER", "SAME_LOWER")
        else "VALID",
        "data_format": "NCHW"}, name)


def _pool(imp, node, ins, reg):
    ks = _a_ints(node, "kernel_shape", [2, 2])
    st = _a_ints(node, "strides", ks)
    padding, pads = _conv_padding(node)
    x = ins[0]
    name = _out_name(node)
    if pads:
        t, l, b, r = (pads + [0, 0, 0, 0])[:4] if len(pads) == 4 \
            else (pads[0], pads[1], pads[0], pads[1])
        cval = -np.inf if reg == "max_pool2d" else 0.0
        x = imp.emit("pad", [x],
                     {"paddings": ((0, 0), (0, 0), (t, b), (l, r)),
                      "constant": cval}, f"{name}_pad")[0]
    return imp.emit(reg, [x], {"kernel": tuple(ks), "strides": tuple(st),
                               "padding": padding, "data_format": "NCHW"},
                    name)


@_mapper("MaxPool")
def _m_max_pool(imp, node, ins):
    return _pool(imp, node, ins, "max_pool2d")


@_mapper("AveragePool")
def _m_avg_pool(imp, node, ins):
    return _pool(imp, node, ins, "avg_pool2d")


@_mapper("GlobalAveragePool")
def _m_gap(imp, node, ins):
    return imp.emit("global_avg_pool", ins,
                    {"data_format": "NCHW", "keep_dims": True},
                    _out_name(node))


@_mapper("GlobalMaxPool")
def _m_gmp(imp, node, ins):
    return imp.emit("global_max_pool", ins,
                    {"data_format": "NCHW", "keep_dims": True},
                    _out_name(node))


@_mapper("BatchNormalization")
def _m_bn(imp, node, ins):
    x, scale, bias, mean, var = ins[:5]
    return imp.emit("batchnorm", [x, mean, var, scale, bias],
                    {"epsilon": _a_f(node, "epsilon", 1e-5), "axis": 1},
                    _out_name(node))


@_mapper("LayerNormalization")
def _m_ln(imp, node, ins):
    if _a_i(node, "axis", -1) not in (-1,):
        raise OnnxImportError("LayerNormalization axis != -1 unsupported")
    return imp.emit("layer_norm", ins[:3],
                    {"axis": -1, "epsilon": _a_f(node, "epsilon", 1e-5)},
                    _out_name(node))


@_mapper("InstanceNormalization")
def _m_inorm(imp, node, ins):
    x, scale, bias = ins
    eps = _a_f(node, "epsilon", 1e-5)
    name = _out_name(node)
    std = imp.emit("standardize", [x], {"axis": (2, 3), "epsilon": eps},
                   f"{name}_std")[0]
    sc = imp.emit("reshape", [scale], {"shape": (1, -1, 1, 1)},
                  f"{name}_sc")[0]
    bi = imp.emit("reshape", [bias], {"shape": (1, -1, 1, 1)},
                  f"{name}_bi")[0]
    y = imp.emit("multiply", [std, sc], {}, f"{name}_m")[0]
    return imp.emit("add", [y, bi], {}, name)


# --- shape / structure -----------------------------------------------------
@_mapper("Shape")
def _m_shape(imp, node, ins):
    shape = imp._static_shape(ins[0], "Shape")
    return _Val(const=np.asarray(shape, np.int64), name=_out_name(node))


@_mapper("Size")
def _m_size(imp, node, ins):
    shape = imp._static_shape(ins[0], "Size")
    return _Val(const=np.asarray(int(np.prod(shape)), np.int64))


@_mapper("Reshape")
def _m_reshape(imp, node, ins):
    shape = list(imp._ints(ins[1], "Reshape shape"))
    if 0 in shape:      # 0 = copy input dim (allowzero=0 default)
        in_shape = imp._static_shape(ins[0], "Reshape")
        shape = [in_shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return imp.emit("reshape", [ins[0]], {"shape": tuple(shape)},
                    _out_name(node))


@_mapper("Flatten")
def _m_flatten(imp, node, ins):
    ax = _a_i(node, "axis", 1)
    if ax == 0:
        return imp.emit("reshape", [ins[0]], {"shape": (1, -1)},
                        _out_name(node))
    if ax == 1:
        # batch dim may be dynamic; only the trailing dims need folding
        return imp.emit("flatten_2d", [ins[0]], {}, _out_name(node))
    in_shape = imp._static_shape(ins[0], "Flatten")
    lead = int(np.prod(in_shape[:ax]))
    return imp.emit("reshape", [ins[0]], {"shape": (lead, -1)},
                    _out_name(node))


@_mapper("Transpose")
def _m_transpose(imp, node, ins):
    perm = _a_ints(node, "perm")
    if not perm:
        nd = len(imp._static_shape(ins[0], "Transpose"))
        perm = list(range(nd))[::-1]
    return imp.emit("permute", [ins[0]], {"axes": tuple(perm)},
                    _out_name(node))


@_mapper("Squeeze")
def _m_squeeze(imp, node, ins):
    axes = _a_ints(node, "axes")
    if len(ins) > 1:
        axes = list(imp._ints(ins[1], "Squeeze axes"))
    return imp.emit("squeeze", [ins[0]],
                    {"axis": tuple(axes) if axes else None},
                    _out_name(node))


@_mapper("Unsqueeze")
def _m_unsqueeze(imp, node, ins):
    axes = _a_ints(node, "axes")
    if len(ins) > 1:
        axes = list(imp._ints(ins[1], "Unsqueeze axes"))
    out = ins[0]
    for i, ax in enumerate(sorted(axes)):
        out = imp.emit("expand_dims", [out], {"axis": ax},
                       f"{_out_name(node)}_{i}" if i < len(axes) - 1
                       else _out_name(node))[0]
    return out


@_mapper("Concat")
def _m_concat(imp, node, ins):
    return imp.emit("concat", ins, {"axis": _a_i(node, "axis", 0)},
                    _out_name(node))


@_mapper("Split")
def _m_split(imp, node, ins):
    axis = _a_i(node, "axis", 0)
    sizes = _a_ints(node, "split")
    if len(ins) > 1:
        sizes = list(imp._ints(ins[1], "Split sizes"))
    n = len(node.outputs)
    if sizes:
        return imp.emit("split_v", [ins[0]],
                        {"sizes": tuple(sizes), "axis": axis},
                        _out_name(node), n_outputs=len(sizes))
    return imp.emit("split", [ins[0]], {"num_split": n, "axis": axis},
                    _out_name(node), n_outputs=n)


@_mapper("Slice")
def _m_slice(imp, node, ins):
    if len(ins) >= 3:        # opset >= 10: starts/ends[/axes/steps] inputs
        starts = list(imp._ints(ins[1], "Slice starts"))
        ends = list(imp._ints(ins[2], "Slice ends"))
        axes = list(imp._ints(ins[3], "Slice axes")) if len(ins) > 3 \
            else list(range(len(starts)))
        steps = list(imp._ints(ins[4], "Slice steps")) if len(ins) > 4 \
            else [1] * len(starts)
    else:                    # opset 1: attributes
        starts = _a_ints(node, "starts")
        ends = _a_ints(node, "ends")
        axes = _a_ints(node, "axes") or list(range(len(starts)))
        steps = [1] * len(starts)
    nd = len(imp._static_shape(ins[0], "Slice"))
    big = 2 ** 31 - 1
    begin = [0] * nd
    end = [big] * nd
    strides = [1] * nd
    for s, e, a, st in zip(starts, ends, axes, steps):
        begin[a], end[a], strides[a] = s, min(e, big), st
    return imp.emit("strided_slice", [ins[0]],
                    {"begin": tuple(begin), "end": tuple(end),
                     "strides": tuple(strides)}, _out_name(node))


@_mapper("Gather")
def _m_gather(imp, node, ins):
    return imp.emit("gather", ins[:2], {"axis": _a_i(node, "axis", 0)},
                    _out_name(node))


@_mapper("GatherND")
def _m_gather_nd(imp, node, ins):
    if _a_i(node, "batch_dims", 0):
        raise OnnxImportError("GatherND batch_dims != 0 unsupported")
    return imp.emit("gather_nd", ins, {}, _out_name(node))


@_mapper("OneHot")
def _m_one_hot(imp, node, ins):
    depth = int(imp._const_np(ins[1], "OneHot depth"))
    values = imp._const_np(ins[2], "OneHot values")   # [off, on]
    return imp.emit("one_hot", [ins[0]],
                    {"depth": depth, "on_value": float(values[1]),
                     "off_value": float(values[0]),
                     "axis": _a_i(node, "axis", -1)}, _out_name(node))


@_mapper("Constant")
def _m_constant(imp, node, ins):
    a = node.attr("value")
    if a is None:
        raise OnnxImportError("Constant without 'value' tensor")
    return _Val(const=a.t, name=_out_name(node))


@_mapper("ConstantOfShape")
def _m_constant_of_shape(imp, node, ins):
    shape = imp._ints(ins[0], "ConstantOfShape shape")
    a = node.attr("value")
    val = a.t if a is not None else np.zeros(1, np.float32)
    return _Val(const=np.full(shape, val.reshape(-1)[0], val.dtype),
                name=_out_name(node))


@_mapper("Expand")
def _m_expand(imp, node, ins):
    shape = imp._ints(ins[1], "Expand shape")
    in_shape = imp._static_shape(ins[0], "Expand")
    # ONNX Expand broadcasts bidirectionally
    out = tuple(max(a, b) for a, b in
                zip((1,) * (len(shape) - len(in_shape)) + tuple(in_shape),
                    shape))
    return imp.emit("broadcast_to", [ins[0]], {"shape": out},
                    _out_name(node))


@_mapper("Tile")
def _m_tile(imp, node, ins):
    return imp.emit("tile", [ins[0]],
                    {"reps": imp._ints(ins[1], "Tile repeats")},
                    _out_name(node))


@_mapper("Pad")
def _m_pad(imp, node, ins):
    mode = _a_s(node, "mode", "constant")
    if len(ins) > 1:
        pads = list(imp._ints(ins[1], "Pad pads"))
    else:
        pads = _a_ints(node, "pads")
    nd = len(pads) // 2
    paddings = [(pads[i], pads[i + nd]) for i in range(nd)]
    const = 0.0
    if len(ins) > 2:
        const = float(imp._const_np(ins[2], "Pad value"))
    return imp.emit("pad", [ins[0]],
                    {"paddings": paddings, "mode": mode,
                     "constant": const}, _out_name(node))


@_mapper("Range")
def _m_range(imp, node, ins):
    s = imp._const_np(ins[0], "Range start")
    l = imp._const_np(ins[1], "Range limit")
    d = imp._const_np(ins[2], "Range delta")
    return _Val(const=np.arange(s, l, d), name=_out_name(node))


@_mapper("CumSum")
def _m_cumsum(imp, node, ins):
    axis = int(imp._const_np(ins[1], "CumSum axis"))
    return imp.emit("cumsum", [ins[0]],
                    {"axis": axis, "exclusive": bool(_a_i(node, "exclusive")),
                     "reverse": bool(_a_i(node, "reverse"))},
                    _out_name(node))


# --- reductions ------------------------------------------------------------
_REDUCE = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
           "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
           "ReduceProd": "reduce_prod", "ReduceL2": "reduce_norm2"}


def _mk_reduce(reg):
    def m(imp, node, ins):
        axes = _a_ints(node, "axes")
        if len(ins) > 1:                      # opset >= 13/18: axes input
            axes = list(imp._ints(ins[1], f"{node.op_type} axes"))
        return imp.emit(reg, [ins[0]],
                        {"axis": tuple(axes) or None,
                         "keep_dims": bool(_a_i(node, "keepdims", 1))},
                        _out_name(node))
    return m


for _o, _r in _REDUCE.items():
    _MAPPERS[_o] = _mk_reduce(_r)


@_mapper("ArgMax")
def _m_argmax(imp, node, ins):
    return imp.emit("argmax", ins,
                    {"axis": _a_i(node, "axis", 0),
                     "keep_dims": bool(_a_i(node, "keepdims", 1))},
                    _out_name(node))


@_mapper("ArgMin")
def _m_argmin(imp, node, ins):
    return imp.emit("argmin", ins,
                    {"axis": _a_i(node, "axis", 0),
                     "keep_dims": bool(_a_i(node, "keepdims", 1))},
                    _out_name(node))


# ---------------------------------------------------------------------------
def import_onnx_model(source: Union[str, bytes, OnnxModel],
                      trainable: Union[None, str, Callable] = None,
                      input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                      ) -> SameDiff:
    """Import an ONNX ModelProto (.onnx path, bytes, or decoded model)
    into a runnable SameDiff graph. Graph outputs keep their ONNX names.

    Reference: samediff-import-onnx OnnxFrameworkImporter →
    ImportGraph.kt:218."""
    if isinstance(source, (str, bytes)):
        model = OnnxModel.from_file(source) if isinstance(source, str) \
            else OnnxModel(source)
    else:
        model = source
    return OnnxImporter(model, trainable=trainable,
                        input_shapes=input_shapes).run()


def supported_onnx_ops() -> List[str]:
    return sorted(set(_MAPPERS) | {"Constant"})
