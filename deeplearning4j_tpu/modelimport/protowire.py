"""Minimal protobuf wire-format decoder for model import.

Reference parity: the reference ships generated protobuf bindings for the
TF/ONNX schemas (nd4j/nd4j-backends/nd4j-api-parent/nd4j-api org.nd4j.ir,
generated from graph.proto et al.) and parses serialized GraphDef/ModelProto
with them (samediff-import-api/.../ImportGraph.kt:218). This framework keeps
the import layer dependency-free instead: the protobuf *wire format* is a
tiny, stable encoding (tag = field<<3|wiretype; varint / 64-bit / length-
delimited / 32-bit payloads), so a ~100-line decoder replaces the generated
binding stack. Schema knowledge (which field number means what) lives in the
per-format view classes in tf_pb.py / onnx_pb.py.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    """Decode one base-128 varint at ``pos``; returns (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long (corrupt protobuf)")


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, raw_value) over one message's bytes.

    Length-delimited values come back as bytes; varints as ints;
    fixed32/fixed64 as their raw little-endian bytes (caller interprets:
    float vs int32 vs double vs int64 is schema knowledge).
    """
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == WIRE_VARINT:
            val, pos = read_varint(data, pos)
        elif wire == WIRE_BYTES:
            ln, pos = read_varint(data, pos)
            val = data[pos:pos + ln]
            if len(val) != ln:
                raise ValueError(
                    f"truncated protobuf: field {field} declares {ln} bytes, "
                    f"{len(val)} available")
            pos += ln
        elif wire == WIRE_FIXED64:
            val = data[pos:pos + 8]
            if len(val) != 8:
                raise ValueError(f"truncated protobuf: fixed64 field {field}")
            pos += 8
        elif wire == WIRE_FIXED32:
            val = data[pos:pos + 4]
            if len(val) != 4:
                raise ValueError(f"truncated protobuf: fixed32 field {field}")
            pos += 4
        elif wire == 3 or wire == 4:  # group start/end (legacy, unused)
            raise ValueError("protobuf groups unsupported")
        else:
            raise ValueError(f"bad wire type {wire} at {pos}")
        yield field, wire, val


class Fields:
    """Decoded message: field number -> list of raw values (wire order)."""

    __slots__ = ("_f",)

    def __init__(self, data: bytes):
        self._f: Dict[int, List] = {}
        for field, _wire, val in iter_fields(data):
            self._f.setdefault(field, []).append(val)

    # scalar accessors (last occurrence wins, per proto3 semantics)
    def varint(self, field: int, default: int = 0) -> int:
        v = self._f.get(field)
        return v[-1] if v else default

    def svarint(self, field: int, default: int = 0) -> int:
        """Signed interpretation of a (non-zigzag) int64 varint."""
        u = self.varint(field, default)
        return u - (1 << 64) if u >= (1 << 63) else u

    def boolean(self, field: int, default: bool = False) -> bool:
        return bool(self.varint(field, int(default)))

    def f32(self, field: int, default: float = 0.0) -> float:
        v = self._f.get(field)
        return struct.unpack("<f", v[-1])[0] if v else default

    def f64(self, field: int, default: float = 0.0) -> float:
        v = self._f.get(field)
        return struct.unpack("<d", v[-1])[0] if v else default

    def bytes_(self, field: int, default: bytes = b"") -> bytes:
        v = self._f.get(field)
        return v[-1] if v else default

    def string(self, field: int, default: str = "") -> str:
        v = self._f.get(field)
        return v[-1].decode("utf-8") if v else default

    def message(self, field: int) -> "Fields | None":
        v = self._f.get(field)
        return Fields(v[-1]) if v else None

    # repeated accessors
    def repeated_bytes(self, field: int) -> List[bytes]:
        return list(self._f.get(field, []))

    def repeated_string(self, field: int) -> List[str]:
        return [b.decode("utf-8") for b in self._f.get(field, [])]

    def repeated_message(self, field: int) -> List["Fields"]:
        return [Fields(b) for b in self._f.get(field, [])]

    def repeated_varint(self, field: int) -> List[int]:
        """Repeated int field: handles both packed and unpacked encodings."""
        out: List[int] = []
        for v in self._f.get(field, []):
            if isinstance(v, int):
                out.append(v)
            else:  # packed: length-delimited blob of varints
                pos = 0
                while pos < len(v):
                    x, pos = read_varint(v, pos)
                    out.append(x)
        return out

    def repeated_svarint(self, field: int) -> List[int]:
        return [x - (1 << 64) if x >= (1 << 63) else x
                for x in self.repeated_varint(field)]

    def repeated_f32(self, field: int) -> List[float]:
        out: List[float] = []
        for v in self._f.get(field, []):
            if len(v) == 4:
                out.append(struct.unpack("<f", v)[0])
            else:  # packed
                out.extend(struct.unpack(f"<{len(v)//4}f", v))
        return out

    def repeated_f64(self, field: int) -> List[float]:
        out: List[float] = []
        for v in self._f.get(field, []):
            if len(v) == 8:
                out.append(struct.unpack("<d", v)[0])
            else:
                out.extend(struct.unpack(f"<{len(v)//8}d", v))
        return out

    def has(self, field: int) -> bool:
        return field in self._f
