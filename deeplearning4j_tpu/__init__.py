"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up re-design of the Eclipse Deeplearning4j capability surface
(reference: /root/reference) for TPU hardware:

- ``ndarray``   : eager NDArray API (reference: nd4j INDArray/Nd4j,
  nd4j-api org.nd4j.linalg) backed by jax.Array — every op is an XLA
  computation rather than a hand-written CUDA/C++ kernel.
- ``ops``       : named-op registry (reference: libnd4j declarable ops +
  legacy op families, libnd4j/include/ops & loops/legacy_ops.h) emitted
  as jax/lax compositions that XLA fuses and tiles onto the MXU.
- ``autodiff``  : SameDiff-equivalent define-then-run graph (reference:
  org.nd4j.autodiff.samediff.SameDiff) that lowers whole training steps
  (forward + backward + fused updater) into ONE compiled XLA computation.
- ``nn``        : layer-based network API (reference: deeplearning4j-nn
  MultiLayerNetwork / NeuralNetConfiguration) compiled through the graph
  layer — there is a single execution path.
- ``learning``  : gradient updaters + LR schedules (reference:
  org.nd4j.linalg.learning).
- ``dataset``/``datavec`` : data pipeline (reference: datavec +
  org.nd4j.linalg.dataset).
- ``evaluation``: metrics (reference: org.nd4j.evaluation).
- ``parallel``  : device-mesh parallelism — DP/TP/PP/sequence parallel via
  jax.sharding + XLA collectives over ICI/DCN (new first-class capability;
  the reference's distributed modules were removed upstream).
- ``models``    : model zoo (reference: deeplearning4j-zoo).
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.ndarray.dtype import DataType
from deeplearning4j_tpu.ndarray.ndarray import NDArray
from deeplearning4j_tpu.ndarray import factory as nd
from deeplearning4j_tpu.environment import Environment, environment
from deeplearning4j_tpu import memory

__all__ = ["DataType", "NDArray", "nd", "Environment", "environment",
           "memory", "__version__"]
