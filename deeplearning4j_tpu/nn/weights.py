"""Weight initialization schemes.

Reference parity: org.deeplearning4j.nn.weights.WeightInit enum +
WeightInitUtil (deeplearning4j-nn nn/weights/) — same variance formulas:
XAVIER = N(0, 2/(fanIn+fanOut)), RELU = N(0, 2/fanIn), LECUN_NORMAL =
N(0, 1/fanIn), *_UNIFORM variants with the matching bounds.
"""
from __future__ import annotations

import math
from typing import Callable, Tuple

import numpy as np


def _fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels HWIO: receptive field * channels
    rf = int(np.prod(shape[:-2]))
    return shape[-2] * rf, shape[-1] * rf


def init_weights(scheme: str, shape: Tuple[int, ...],
                 rng: np.random.Generator) -> np.ndarray:
    scheme = scheme.upper()
    fan_in, fan_out = _fans(tuple(shape))
    if scheme == "ZERO":
        return np.zeros(shape)
    if scheme == "ONES":
        return np.ones(shape)
    if scheme == "IDENTITY":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY needs a square 2d shape")
        return np.eye(shape[0])
    if scheme == "NORMAL":
        return rng.normal(0.0, 1.0 / math.sqrt(fan_in), shape)
    if scheme == "XAVIER":
        return rng.normal(0.0, math.sqrt(2.0 / (fan_in + fan_out)), shape)
    if scheme == "XAVIER_UNIFORM":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return rng.uniform(-a, a, shape)
    if scheme == "RELU":
        return rng.normal(0.0, math.sqrt(2.0 / fan_in), shape)
    if scheme == "RELU_UNIFORM":
        a = math.sqrt(6.0 / fan_in)
        return rng.uniform(-a, a, shape)
    if scheme == "LECUN_NORMAL":
        return rng.normal(0.0, math.sqrt(1.0 / fan_in), shape)
    if scheme == "LECUN_UNIFORM":
        a = math.sqrt(3.0 / fan_in)
        return rng.uniform(-a, a, shape)
    if scheme == "UNIFORM":
        a = 1.0 / math.sqrt(fan_in)
        return rng.uniform(-a, a, shape)
    if scheme == "SIGMOID_UNIFORM":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return rng.uniform(-a, a, shape)
    if scheme == "VAR_SCALING_NORMAL_FAN_AVG":
        return rng.normal(0.0, math.sqrt(2.0 / (fan_in + fan_out)), shape)
    raise ValueError(f"unknown weight init scheme: {scheme}")


ALL_SCHEMES = ["ZERO", "ONES", "IDENTITY", "NORMAL", "XAVIER",
               "XAVIER_UNIFORM", "RELU", "RELU_UNIFORM", "LECUN_NORMAL",
               "LECUN_UNIFORM", "UNIFORM", "SIGMOID_UNIFORM"]
