"""Activation resolution for layer configs.

Reference parity: org.nd4j.linalg.activations.Activation enum (IActivation
impls under nd4j linalg/activations/impl) — names map onto registry ops.
"""
from __future__ import annotations

_ALIASES = {
    "identity": "identity",
    "linear": "identity",
    "relu": "relu",
    "relu6": "relu6",
    "leakyrelu": "leaky_relu",
    "leaky_relu": "leaky_relu",
    "elu": "elu",
    "selu": "selu",
    "gelu": "gelu",
    "sigmoid": "sigmoid",
    "hardsigmoid": "hard_sigmoid",
    "hard_sigmoid": "hard_sigmoid",
    "tanh": "tanh",
    "hardtanh": "hard_tanh",
    "hard_tanh": "hard_tanh",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "swish": "swish",
    "mish": "mish",
    "cube": "cube",
    "thresholdedrelu": "thresholdedrelu",
    "thresholded_relu": "thresholdedrelu",
    "rationaltanh": "rationaltanh",
    "rectifiedtanh": "rectifiedtanh",
}


def resolve_activation(name: str) -> str:
    """Activation name -> registry op name."""
    key = name.lower()
    if key not in _ALIASES:
        raise ValueError(f"unknown activation {name!r}; "
                         f"known: {sorted(set(_ALIASES))}")
    return _ALIASES[key]


def apply_activation(sd, x, name: str, layer_name: str = None):
    op = resolve_activation(name)
    if op == "identity":
        return x
    kwargs = {"name": f"{layer_name}_act" if layer_name else None}
    return sd.invoke(op, [x], {}, **kwargs)
