"""ComputationGraph — DAG networks with multiple inputs/outputs.

Reference parity: org.deeplearning4j.nn.graph.ComputationGraph
(ComputationGraph.java) + ComputationGraphConfiguration.GraphBuilder
(nn/conf/ComputationGraphConfiguration.java) + graph vertices
(nn/conf/graph/: MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex,
ShiftVertex, L2NormalizeVertex, StackVertex, UnstackVertex, …).

Same single-execution-path design as MultiLayerNetwork: the whole DAG
records into one SameDiff graph per mode (train/infer) and compiles to one
XLA computation; the reference's per-vertex forward/backprop scheduling
(topological GraphVertex.doForward/doBackward) is replaced by trace order +
jax.grad.
"""
from __future__ import annotations

import dataclasses
import io
import json
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff import (MixedPrecision, SameDiff,
                                         TrainingConfig)
from deeplearning4j_tpu.learning.updaters import IUpdater, Sgd
from deeplearning4j_tpu.learning.regularization import Regularization
from deeplearning4j_tpu.nn.layers import (
    BaseLayer, BuildContext, InputType, LAYER_TYPES)


# ----------------------------------------------------------------------
# graph vertices (reference: nn/conf/graph/*Vertex)
class GraphVertex:
    def build(self, ctx: BuildContext, xs: List, itypes: List[InputType]):
        raise NotImplementedError

    def output_type(self, itypes: List[InputType]) -> InputType:
        raise NotImplementedError

    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d

    @staticmethod
    def from_json(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_TYPES[d.pop("@class")]
        kw = {f.name: tuple(d[f.name]) if isinstance(d.get(f.name), list)
              else d[f.name]
              for f in dataclasses.fields(cls) if f.name in d}
        return cls(**kw)


@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concat along the feature axis (reference: MergeVertex)."""

    def output_type(self, itypes):
        kind = itypes[0].kind
        if kind == "ff":
            return InputType.feed_forward(sum(t.dims[0] for t in itypes))
        if kind == "cnn":
            c = sum(t.dims[0] for t in itypes)
            return InputType("cnn", (c,) + itypes[0].dims[1:])
        if kind == "rnn":
            return InputType.recurrent(sum(t.dims[0] for t in itypes),
                                       itypes[0].dims[1])
        raise ValueError(kind)

    def build(self, ctx, xs, itypes):
        if itypes[0].kind == "cnn" and ctx.cnn_format == "NHWC":
            axis = 3          # channels-last runtime layout
        elif itypes[0].kind in ("ff", "cnn"):
            axis = 1
        else:
            axis = 2
        out = ctx.sd.invoke("concat", xs, {"axis": axis},
                            name=ctx.lname("merge"))
        return out, self.output_type(itypes)


@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise combine (reference: ElementWiseVertex Op.{Add,Subtract,
    Product,Average,Max})."""
    op: str = "Add"

    def output_type(self, itypes):
        return itypes[0]

    def build(self, ctx, xs, itypes):
        name = ctx.lname("elementwise")
        op = self.op.lower()
        if op == "average":
            acc = xs[0]
            for x in xs[1:]:
                acc = acc.add(x)
            out = acc.mul(ctx.sd.constant(1.0 / len(xs), f"{name}_scale"),
                          name=name)
        elif op == "max":
            acc = xs[0]
            for i, x in enumerate(xs[1:]):
                acc = ctx.sd.invoke("maximum", [acc, x], {},
                                    name=f"{name}_{i}")
            out = acc
        else:
            fn = {"add": "add", "subtract": "subtract",
                  "product": "multiply"}[op]
            acc = xs[0]
            for i, x in enumerate(xs[1:]):
                acc = ctx.sd.invoke(fn, [acc, x], {}, name=f"{name}_{i}")
            out = acc
        return out, itypes[0]


@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive on the FEATURE axis
    (reference: SubsetVertex subsets features for any input kind)."""
    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, itypes):
        t = itypes[0]
        n = self.to_idx - self.from_idx + 1
        if t.kind == "ff":
            return InputType.feed_forward(n)
        if t.kind == "cnn":
            return InputType("cnn", (n,) + t.dims[1:])
        if t.kind == "rnn":
            return InputType.recurrent(n, t.dims[1])
        raise ValueError(t.kind)

    def build(self, ctx, xs, itypes):
        x = xs[0]
        t = itypes[0]
        big = 2 ** 31 - 1
        # feature axis: 1 for ff / cnn-NCHW, 3 for cnn-NHWC runtime
        # tensors, 2 for rnn (B, T, C)
        if t.kind == "cnn" and ctx.cnn_format == "NHWC":
            begin = (0, 0, 0, self.from_idx)
            end = (big, big, big, self.to_idx + 1)
        elif t.kind in ("ff", "cnn"):
            ndim = 2 if t.kind == "ff" else 4
            begin = (0, self.from_idx) + (0,) * (ndim - 2)
            end = (big, self.to_idx + 1) + (big,) * (ndim - 2)
        else:
            begin = (0, 0, self.from_idx)
            end = (big, big, self.to_idx + 1)
        out = ctx.sd.invoke("strided_slice", [x], {"begin": begin, "end": end},
                            name=ctx.lname("subset"))
        return out, self.output_type(itypes)


@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    scale_factor: float = 1.0

    def output_type(self, itypes):
        return itypes[0]

    def build(self, ctx, xs, itypes):
        name = ctx.lname("scale")
        out = xs[0].mul(ctx.sd.constant(self.scale_factor, f"{name}_c"),
                        name=name)
        return out, itypes[0]


@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    shift_factor: float = 0.0

    def output_type(self, itypes):
        return itypes[0]

    def build(self, ctx, xs, itypes):
        name = ctx.lname("shift")
        out = xs[0].add(ctx.sd.constant(self.shift_factor, f"{name}_c"),
                        name=name)
        return out, itypes[0]


@dataclasses.dataclass
class DotProductVertex(GraphVertex):
    """Batch dot product of two inputs over the FEATURE axis, with
    optional L2 normalization first (imports Keras's Dot merge layer;
    the cosine-similarity head of siamese nets).

    The runtime feature axis depends on input kind and layout (see
    SubsetVertex): ff → axis 1 yielding (B, 1); rnn (B, T, C) → axis 2
    yielding a per-timestep scalar sequence (B, T, 1)."""
    normalize: bool = False

    def output_type(self, itypes):
        from deeplearning4j_tpu.nn.layers import InputType
        t = itypes[0]
        if t.kind == "ff":
            return InputType.feed_forward(1)
        if t.kind == "rnn":
            return InputType.recurrent(1, t.dims[1])
        raise ValueError(
            f"DotProductVertex supports ff/rnn inputs, not {t.kind!r}")

    def build(self, ctx, xs, itypes):
        name = ctx.lname("dot")
        a, b = xs[0], xs[1]
        t = itypes[0]
        axis = 1 if t.kind == "ff" else 2        # runtime feature axis
        if self.normalize:
            eps = ctx.sd.constant(1e-12, f"{name}_eps")
            a = a.div(a.square().sum(dims=(axis,), keep_dims=True)
                      .sqrt().add(eps), name=f"{name}_na")
            b = b.div(b.square().sum(dims=(axis,), keep_dims=True)
                      .sqrt().add(eps), name=f"{name}_nb")
        out = a.mul(b).sum(dims=(axis,), keep_dims=True, name=name)
        return out, self.output_type(itypes)


@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """Normalizes over all non-batch dimensions by default, matching the
    reference L2NormalizeVertex (nn/conf/graph/L2NormalizeVertex.java);
    pass ``dimensions`` to restrict."""
    eps: float = 1e-8
    dimensions: Optional[Tuple[int, ...]] = None

    def output_type(self, itypes):
        return itypes[0]

    def build(self, ctx, xs, itypes):
        name = ctx.lname("l2norm")
        x = xs[0]
        # input rank = batch axis + itype dims (ff:2, recurrent:3, cnn:4)
        rank = 1 + len(itypes[0].dims)
        dims = tuple(self.dimensions) if self.dimensions is not None \
            else tuple(range(1, rank))
        norm = x.square().sum(dims=dims, keep_dims=True).sqrt()
        out = x.div(norm.add(ctx.sd.constant(self.eps, f"{name}_eps")),
                    name=name)
        return out, itypes[0]


VERTEX_TYPES: Dict[str, type] = {c.__name__: c for c in [
    MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex, ShiftVertex,
    L2NormalizeVertex, DotProductVertex,
]}


# ----------------------------------------------------------------------
@dataclasses.dataclass
class _Node:
    name: str
    op: object                # BaseLayer or GraphVertex
    inputs: List[str]


@dataclasses.dataclass
class ComputationGraphConfiguration:
    inputs: List[str]
    input_types: List[InputType]
    nodes: List[_Node]
    outputs: List[str]
    seed: int = 12345
    updater: IUpdater = dataclasses.field(default_factory=lambda: Sgd(0.01))
    regularization: Sequence[Regularization] = ()
    dtype: str = "float32"
    mixed_precision: Optional[object] = None    # MixedPrecision policy
    # internal cnn layout ("NHWC" = TPU-native; see MultiLayerConfiguration)
    cnn_data_format: str = "NHWC"

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed, "dtype": self.dtype,
            "cnn_data_format": self.cnn_data_format,
            "mixed_precision": (self.mixed_precision.to_json()
                                if self.mixed_precision else None),
            "updater": self.updater.to_json(),
            "regularization": [r.to_json() for r in self.regularization],
            "inputs": self.inputs,
            "input_types": [t.to_json() for t in self.input_types],
            "outputs": self.outputs,
            "nodes": [{"name": n.name,
                       "kind": "layer" if isinstance(n.op, BaseLayer) else "vertex",
                       "op": n.op.to_json(), "inputs": n.inputs}
                      for n in self.nodes],
        }, indent=1)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        nodes = []
        for nd in d["nodes"]:
            op = BaseLayer.from_json(nd["op"]) if nd["kind"] == "layer" \
                else GraphVertex.from_json(nd["op"])
            nodes.append(_Node(nd["name"], op, list(nd["inputs"])))
        return ComputationGraphConfiguration(
            inputs=list(d["inputs"]),
            input_types=[InputType.from_json(t) for t in d["input_types"]],
            nodes=nodes, outputs=list(d["outputs"]), seed=d["seed"],
            updater=IUpdater.from_json(d["updater"]),
            regularization=[Regularization.from_json(r)
                            for r in d.get("regularization", [])],
            dtype=d.get("dtype", "float32"),
            cnn_data_format=d.get("cnn_data_format", "NCHW"),
            mixed_precision=MixedPrecision.from_json(
                d.get("mixed_precision")))


class GraphBuilder:
    """Reference: ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, parent=None):
        self._parent = parent
        self._inputs: List[str] = []
        self._input_types: List[InputType] = []
        self._nodes: List[_Node] = []
        self._outputs: List[str] = []

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def add_layer(self, name: str, layer: BaseLayer,
                  *inputs: str) -> "GraphBuilder":
        self._nodes.append(_Node(name, layer, list(inputs)))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex,
                   *inputs: str) -> "GraphBuilder":
        self._nodes.append(_Node(name, vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs or not self._outputs:
            raise ValueError("graph needs add_inputs(...) and set_outputs(...)")
        if len(self._input_types) != len(self._inputs):
            raise ValueError("set_input_types must match add_inputs")
        if len(set(self._inputs)) != len(self._inputs):
            raise ValueError("duplicate input names")
        known = set(self._inputs)
        for n in self._nodes:
            if n.name in known:
                raise ValueError(f"duplicate node name {n.name!r} "
                                 f"(or it shadows an input)")
            if isinstance(n.op, BaseLayer) and len(n.inputs) > 1:
                raise ValueError(
                    f"layer node {n.name!r} has {len(n.inputs)} inputs; "
                    f"layers take one — insert a MergeVertex (the reference "
                    f"auto-merges; here it is explicit)")
            for i in n.inputs:
                if i not in known:
                    raise ValueError(f"node {n.name!r} references unknown "
                                     f"input {i!r} (define nodes in "
                                     f"topological order)")
            known.add(n.name)
        for o in self._outputs:
            if o not in known:
                raise ValueError(f"unknown output {o!r}")
        p = self._parent
        kw = {}
        if p is not None:
            kw = {"seed": p._seed, "updater": p._updater, "dtype": p._dtype,
                  "mixed_precision": p._mixed_precision}
            regs = []
            from deeplearning4j_tpu.learning.regularization import (
                L1Regularization, L2Regularization, WeightDecay)
            if p._l1:
                regs.append(L1Regularization(l1=p._l1))
            if p._l2:
                regs.append(L2Regularization(l2=p._l2))
            if p._weight_decay:
                regs.append(WeightDecay(coeff=p._weight_decay))
            kw["regularization"] = regs
        return ComputationGraphConfiguration(
            inputs=self._inputs, input_types=self._input_types,
            nodes=self._nodes, outputs=self._outputs, **kw)


def _build_graph(conf: ComputationGraphConfiguration, training: bool):
    """Returns (sd, label placeholder names in conf.outputs order,
    node name -> actual graph variable name map)."""
    from deeplearning4j_tpu.nn.multilayer import (
        _adapt_input, _to_external_layout, _to_internal_layout)
    sd = SameDiff()
    rng = np.random.default_rng(conf.seed)
    fmt = getattr(conf, "cnn_data_format", "NHWC")
    ctx = BuildContext(sd=sd, rng=rng, training=training, dtype=conf.dtype,
                       cnn_format=fmt)
    vars_: Dict[str, object] = {}
    types_: Dict[str, InputType] = {}
    for name, itype in zip(conf.inputs, conf.input_types):
        ph = sd.placeholder(name, shape=itype.placeholder_shape(),
                            dtype=conf.dtype)
        vars_[name] = _to_internal_layout(sd, ph, itype, fmt,
                                          f"{name}_nhwc")
        types_[name] = itype

    labels_of: Dict[str, str] = {}   # loss node name -> labels placeholder
    for node in conf.nodes:
        ctx.prefix = node.name
        if isinstance(node.op, BaseLayer):
            x = vars_[node.inputs[0]]
            itype = types_[node.inputs[0]]
            x, itype = _adapt_input(sd, x, itype, node.op, node.name,
                                    name_stem=f"{node.name}_preproc")
            if hasattr(node.op, "loss_function") or \
                    getattr(node.op, "consumes_labels", False):
                # labels placeholder sized from this head's output type
                # (heads with a different target layout override via
                # labels_placeholder_shape — see nn/multilayer.py)
                otype = node.op.output_type(itype)
                ln = f"labels_{node.name}"
                lab_hook = getattr(node.op, "labels_placeholder_shape",
                                   None)
                lab_shape = lab_hook(otype) if lab_hook is not None \
                    else None
                ctx.labels_var = sd.placeholder(
                    ln,
                    shape=lab_shape if lab_shape is not None
                    else otype.placeholder_shape(), dtype=conf.dtype)
                labels_of[node.name] = ln
            out, otype = node.op.build(ctx, x, itype)
        else:
            xs = [vars_[i] for i in node.inputs]
            its = [types_[i] for i in node.inputs]
            out, otype = node.op.build(ctx, xs, its)
        # passthrough builds (identity activation, inference dropout, …)
        # return an upstream var — alias it rather than renaming, which
        # would corrupt the upstream name
        vars_[node.name] = out
        types_[node.name] = otype

    # cnn-typed graph outputs go back to the external NCHW contract
    for oname in conf.outputs:
        if types_[oname].kind in ("cnn", "cnn3d"):
            vars_[oname] = _to_external_layout(
                sd, vars_[oname], types_[oname], fmt, f"{oname}_nchw")

    # labels in conf.outputs order first (matches user-supplied label
    # lists), then any non-output loss heads in node order
    ordered = [n for n in conf.outputs if n in labels_of] + \
              [n for n in (nd.name for nd in conf.nodes)
               if n in labels_of and n not in conf.outputs]
    label_names = [labels_of[n] for n in ordered]
    name_map = {n: vars_[n].name for n in vars_}
    return sd, label_names, name_map


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._sd_train: Optional[SameDiff] = None
        self._sd_infer: Optional[SameDiff] = None
        self._label_names: List[str] = []
        self._map_train: Dict[str, str] = {}
        self._map_infer: Dict[str, str] = {}
        self._score = float("nan")

    def init(self) -> "ComputationGraph":
        self._sd_train, self._label_names, self._map_train = \
            _build_graph(self.conf, True)
        self._sd_infer, _, self._map_infer = _build_graph(self.conf, False)
        self._sd_train.training_config = TrainingConfig(
            updater=self.conf.updater,
            data_set_feature_mapping=list(self.conf.inputs),
            data_set_label_mapping=list(self._label_names),
            regularization=self.conf.regularization,
            mixed_precision=self.conf.mixed_precision,
        )
        return self

    @property
    def samediff(self) -> SameDiff:
        return self._sd_train

    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32,
            listeners: Sequence = (), fused_steps: Optional[int] = None,
            accum_steps: Optional[int] = None,
            sentinel: Optional[bool] = None):
        """Train. ``data`` = iterator of (features-list, labels-list) /
        MultiDataSet / dict batches; or single-input arrays with labels=.

        ``fused_steps``/``accum_steps`` override the TrainingConfig knobs
        for this and subsequent fits — the fused-window execution tier
        (docs/training_performance.md). ``sentinel`` arms the device-side
        divergence sentinel (docs/fault_tolerance.md)."""
        if fused_steps is not None:
            self._sd_train.training_config.fused_steps = int(fused_steps)
        if accum_steps is not None:
            self._sd_train.training_config.accum_steps = int(accum_steps)
        if sentinel is not None:
            self._sd_train.training_config.sentinel = bool(sentinel)
        if labels is not None:
            from deeplearning4j_tpu.nn.multilayer import _ArrayIterator
            data = _ArrayIterator(np.asarray(data), np.asarray(labels),
                                  batch_size)
        history = self._sd_train.fit(data, epochs=epochs, listeners=listeners)
        self._score = history.final_loss()
        return history

    def _sync_infer(self):
        tgt = self._sd_infer
        for n, arr in self._sd_train._arrays.items():
            if n in tgt._vars and n in tgt._arrays:
                tgt._arrays[n] = arr

    def serving_spec(self):
        """Replica-extraction hook for the serving/ subsystem: the
        inference graph, declared input names, resolved output variable
        names, and the parameter sync (see
        MultiLayerNetwork.serving_spec)."""
        if self._sd_infer is None:
            raise RuntimeError("call init() first")
        out_names = [self._map_infer[o] for o in self.conf.outputs]
        return (self._sd_infer, list(self.conf.inputs), out_names,
                self._sync_infer)

    def output(self, *inputs, training: bool = False):
        """Forward pass; returns list of output NDArrays (reference:
        ComputationGraph.output(INDArray...))."""
        sd = self._sd_train if training else self._sd_infer
        name_map = self._map_train if training else self._map_infer
        if not training:
            self._sync_infer()
        ph = dict(zip(self.conf.inputs, inputs))
        out_names = [name_map[o] for o in self.conf.outputs]
        res = sd.output(ph, out_names)
        return [res[n] for n in out_names]

    def feed_forward(self, *inputs, training: bool = False
                     ) -> Dict[str, object]:
        """Forward pass returning the activation of EVERY named vertex
        (reference: ComputationGraph.feedForward() -> Map<String,INDArray>).
        cnn-typed intermediates stay in the internal layout."""
        sd = self._sd_train if training else self._sd_infer
        name_map = self._map_train if training else self._map_infer
        if not training:
            self._sync_infer()
        ph = dict(zip(self.conf.inputs, inputs))
        res = sd.output(ph, list(set(name_map.values())))
        return {n: res[v] for n, v in name_map.items()}

    def score(self) -> float:
        return self._score

    def params(self) -> Dict[str, np.ndarray]:
        return {n: np.asarray(a) for n, a in
                {**self._sd_train.trainable_params(),
                 **self._sd_train.state_vars_map()}.items()}

    def num_params(self) -> int:
        return sum(int(np.prod(a.shape))
                   for a in self._sd_train.trainable_params().values())

    def summary(self) -> str:
        """Vertex table (reference: ComputationGraph.summary())."""
        lines = [f"ComputationGraph: {len(self.conf.nodes)} vertices, "
                 f"inputs {list(self.conf.inputs)}, outputs "
                 f"{list(self.conf.outputs)}, "
                 f"{self.num_params() if self._sd_train else '?'} params"]
        for node in self.conf.nodes:
            kind = type(node.op).__name__
            lines.append(f"  {node.name:<24} {kind:<28} "
                         f"<- {', '.join(node.inputs)}")
        return "\n".join(lines)

    def evaluate(self, iterator, evaluation=None):
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = evaluation or Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for batch in iterator:
            if hasattr(batch, "features"):
                feats, labs = batch.features, batch.labels
            else:
                feats, labs = batch
            feats = feats if isinstance(feats, (list, tuple)) else [feats]
            labs = labs if isinstance(labs, (list, tuple)) else [labs]
            preds = self.output(*feats)
            ev.eval(labs[0], preds[0])
        return ev

    # --- checkpointing (checkpoint/ subsystem) ------------------------
    def capture_training_state(self, epoch: int = 0, normalizer=None):
        """Host snapshot for the checkpoint manager
        (checkpoint.capture_training_state)."""
        from deeplearning4j_tpu.checkpoint import capture_training_state
        return capture_training_state(self, epoch=epoch,
                                      normalizer=normalizer)

    def restore_training_state(self, state, strict: bool = True):
        """Restore a TrainingState snapshot into this initialized graph."""
        from deeplearning4j_tpu.checkpoint import restore_training_state
        return restore_training_state(self, state, strict=strict)

    # --- serde --------------------------------------------------------
    def save(self, path, include_updater_state: bool = True) -> None:
        from deeplearning4j_tpu.nn.model_serde import save_net_zip
        save_net_zip(path, self.conf.to_json(), self._sd_train,
                     include_updater_state)

    @staticmethod
    def load(path) -> "ComputationGraph":
        from deeplearning4j_tpu.nn.model_serde import (read_net_zip,
                                                       restore_net_state)
        conf_json, arrays, updater_leaves, iteration = read_net_zip(path)
        conf = ComputationGraphConfiguration.from_json(conf_json)
        net = ComputationGraph(conf).init()
        return restore_net_state(net, conf, arrays, updater_leaves, iteration)


