"""Network configuration DSL.

Reference parity: org.deeplearning4j.nn.conf.NeuralNetConfiguration
(builder + Jackson JSON serde) and MultiLayerConfiguration. The builder
shape follows the reference —

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Adam(1e-3))
            .l2(1e-4)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())

— but the built artifact compiles to one SameDiff graph rather than a stack
of imperative layer objects (there is no second execution path; the
reference's nn/layers/samediff bridge is the *only* path here).
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence

from deeplearning4j_tpu.autodiff.training import MixedPrecision
from deeplearning4j_tpu.learning.updaters import IUpdater, Sgd
from deeplearning4j_tpu.learning.regularization import (
    L1Regularization, L2Regularization, Regularization, WeightDecay)
from deeplearning4j_tpu.nn.layers import BaseLayer, InputType


@dataclasses.dataclass
class MultiLayerConfiguration:
    layers: List[BaseLayer]
    input_type: InputType
    seed: int = 12345
    updater: IUpdater = dataclasses.field(default_factory=lambda: Sgd(0.01))
    regularization: Sequence[Regularization] = ()
    dtype: str = "float32"
    grad_clip_value: Optional[float] = None
    mixed_precision: Optional[MixedPrecision] = None
    # internal cnn tensor layout; "NHWC" is TPU-native (12x conv speedup vs
    # logical NCHW, see PROFILE.md). External API stays NCHW either way.
    # from_json defaults to "NCHW" so checkpoints saved before this field
    # existed keep their trained flatten-order weights valid.
    cnn_data_format: str = "NHWC"
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0

    # --- serde (reference: MultiLayerConfiguration.toJson/fromJson) -----
    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "dtype": self.dtype,
            "cnn_data_format": self.cnn_data_format,
            "grad_clip_value": self.grad_clip_value,
            "mixed_precision": (self.mixed_precision.to_json()
                                if self.mixed_precision else None),
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
            "updater": self.updater.to_json(),
            "regularization": [r.to_json() for r in self.regularization],
            "input_type": self.input_type.to_json(),
            "layers": [l.to_json() for l in self.layers],
        }, indent=1)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        return MultiLayerConfiguration(
            layers=[BaseLayer.from_json(ld) for ld in d["layers"]],
            input_type=InputType.from_json(d["input_type"]),
            seed=d.get("seed", 12345),
            updater=IUpdater.from_json(d["updater"]),
            regularization=[Regularization.from_json(r)
                            for r in d.get("regularization", [])],
            dtype=d.get("dtype", "float32"),
            cnn_data_format=d.get("cnn_data_format", "NCHW"),
            grad_clip_value=d.get("grad_clip_value"),
            mixed_precision=MixedPrecision.from_json(d.get("mixed_precision")),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
        )


class ListBuilder:
    def __init__(self, parent: "NeuralNetConfiguration.Builder"):
        self._parent = parent
        self._layers: List[BaseLayer] = []
        self._input_type: Optional[InputType] = None

    def layer(self, layer: BaseLayer) -> "ListBuilder":
        self._layers.append(layer)
        return self

    def set_input_type(self, itype: InputType) -> "ListBuilder":
        self._input_type = itype
        return self

    def build(self) -> MultiLayerConfiguration:
        if self._input_type is None:
            raise ValueError("set_input_type(...) is required (the reference "
                             "infers nIn via setInputType the same way)")
        p = self._parent
        regs: List[Regularization] = []
        if p._l1:
            regs.append(L1Regularization(l1=p._l1))
        if p._l2:
            regs.append(L2Regularization(l2=p._l2))
        if p._weight_decay:
            regs.append(WeightDecay(coeff=p._weight_decay))
        return MultiLayerConfiguration(
            layers=self._layers, input_type=self._input_type, seed=p._seed,
            updater=p._updater, regularization=regs, dtype=p._dtype,
            grad_clip_value=p._grad_clip, mixed_precision=p._mixed_precision,
            gradient_normalization=p._grad_norm,
            gradient_normalization_threshold=p._grad_norm_threshold)


class NeuralNetConfiguration:
    class Builder:
        def __init__(self):
            self._seed = 12345
            self._updater: IUpdater = Sgd(0.01)
            self._l1 = 0.0
            self._l2 = 0.0
            self._weight_decay = 0.0
            self._dtype = "float32"
            self._grad_clip = None
            self._mixed_precision = None
            self._grad_norm = None
            self._grad_norm_threshold = 1.0

        def seed(self, s: int):            self._seed = int(s); return self
        def updater(self, u: IUpdater):    self._updater = u; return self
        def l1(self, v: float):            self._l1 = v; return self
        def l2(self, v: float):            self._l2 = v; return self
        def weight_decay(self, v: float):  self._weight_decay = v; return self
        def data_type(self, dt: str):      self._dtype = dt; return self
        def gradient_clip(self, v: float): self._grad_clip = v; return self

        def mixed_precision(self, mp=True):
            """bf16-compute / f32-master-param training policy (pass a
            MixedPrecision for a custom compute dtype / loss scale)."""
            self._mixed_precision = MixedPrecision() if mp is True else mp
            return self

        def gradient_normalization(self, mode: str, threshold: float = 1.0):
            """clip_l2_per_layer | clip_l2_global | renormalize_l2_per_layer
            | clip_element_wise_absolute_value (reference:
            GradientNormalization enum, BaseMultiLayerUpdater.preApply)."""
            self._grad_norm = mode
            self._grad_norm_threshold = threshold
            return self

        def list(self) -> ListBuilder:
            return ListBuilder(self)

        def graph_builder(self):
            """DAG configuration (reference:
            NeuralNetConfiguration.Builder().graphBuilder())."""
            from deeplearning4j_tpu.nn.graph import GraphBuilder
            return GraphBuilder(self)

    @staticmethod
    def builder() -> "NeuralNetConfiguration.Builder":
        return NeuralNetConfiguration.Builder()
