"""Shared zip-container serde for layer-based networks.

Reference parity: util/ModelSerializer.java — a zip of configuration JSON,
flattened parameters, updater state, and training iteration count. Both
MultiLayerNetwork and ComputationGraph write the same container format
through these helpers.
"""
from __future__ import annotations

import io
import json
import zipfile

import numpy as np


def save_net_zip(path, conf_json: str, sd, include_updater_state: bool = True
                 ) -> None:
    """Write the ModelSerializer-style container for a network whose
    parameters live in SameDiff graph ``sd``.

    Crash-safe: the zip is assembled in a temp file next to ``path`` and
    atomically renamed into place (checkpoint/atomic.py), so a killed
    process never leaves a torn zip at the target — the previous file,
    if any, stays intact until the new one is complete."""
    from deeplearning4j_tpu.checkpoint.atomic import atomic_output_file
    with atomic_output_file(path) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", conf_json)
            buf = io.BytesIO()
            np.savez(buf, **{n: np.asarray(a) for n, a in sd._arrays.items()
                             if n in sd._vars})
            zf.writestr("parameters.npz", buf.getvalue())
            if include_updater_state and sd._updater_state is not None:
                import jax
                leaves = jax.tree_util.tree_leaves(sd._updater_state)
                buf = io.BytesIO()
                np.savez(buf, **{f"leaf_{i}": np.asarray(l)
                                 for i, l in enumerate(leaves)})
                zf.writestr("updater.npz", buf.getvalue())
            zf.writestr("iteration.json", json.dumps({
                "iteration_count": sd.training_config.iteration_count
                if sd.training_config else 0}))


def read_net_zip(path):
    """Read the container → (conf_json, arrays, updater_leaves, iteration)."""
    import jax.numpy as jnp
    with zipfile.ZipFile(path, "r") as zf:
        conf_json = zf.read("configuration.json").decode()
        with np.load(io.BytesIO(zf.read("parameters.npz"))) as npz:
            arrays = {k: jnp.asarray(npz[k]) for k in npz.files}
        updater_leaves = None
        if "updater.npz" in zf.namelist():
            with np.load(io.BytesIO(zf.read("updater.npz"))) as npz:
                updater_leaves = [jnp.asarray(npz[f"leaf_{i}"])
                                  for i in range(len(npz.files))]
        iteration = 0
        if "iteration.json" in zf.namelist():
            iteration = json.loads(zf.read("iteration.json"))\
                .get("iteration_count", 0)
    return conf_json, arrays, updater_leaves, iteration


def restore_net_state(net, conf, arrays, updater_leaves, iteration):
    """Copy loaded arrays/updater state/iteration into an initialized net."""
    import jax
    sd = net._sd_train
    for n, arr in arrays.items():
        if n in sd._vars:
            sd._arrays[n] = arr
    if updater_leaves is not None:
        template = conf.updater.init(sd.trainable_params())
        treedef = jax.tree_util.tree_structure(template)
        sd._updater_state = jax.tree_util.tree_unflatten(
            treedef, updater_leaves)
    if sd.training_config is not None:
        sd.training_config.iteration_count = iteration
    return net
