"""Recurrent layer breadth: SimpleRnn, Bidirectional wrapper,
RnnOutputLayer, LastTimeStep.

Reference parity: nn/conf/layers/{recurrent/SimpleRnn, recurrent/
Bidirectional, RnnOutputLayer, recurrent/LastTimeStep}.java. TPU-native:
recurrences are lax.scan under the named ops (one XLA While loop), the
bidirectional wrapper runs the wrapped layer on a time-reversed copy and
merges — XLA schedules both directions in one computation.

Sequence layout: (batch, time, features).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.layers import (
    BaseLayer, InputType, LAYER_TYPES, _attach_loss_head, _maybe_dropout)


@dataclasses.dataclass
class SimpleRnnLayer(BaseLayer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} U + b) (reference:
    nn/conf/layers/recurrent/SimpleRnn)."""
    n_out: int = 0
    activation: str = "tanh"
    weight_init: str = "XAVIER"
    return_sequences: bool = True
    dropout: float = 0.0

    def output_type(self, itype):
        if self.return_sequences:
            return InputType.recurrent(self.n_out, itype.dims[1])
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("rnn")
        n_in = itype.dims[0]
        u = self.n_out
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w = ctx.param(f"{lname}_W", (n_in, u), self.weight_init)
        r = ctx.param(f"{lname}_U", (u, u), self.weight_init)
        b = ctx.sd.var(f"{lname}_b", value=np.zeros((u,)), dtype=ctx.dtype)
        from deeplearning4j_tpu.nn.layers import (_rnn_carry_states,
                                                  _rnn_initial_states)
        h0, = _rnn_initial_states(ctx, lname, x, u)
        from deeplearning4j_tpu.nn.activations import resolve_activation
        out, hT = ctx.sd.invoke(
            "simple_rnn_layer", [x, h0, w, r, b],
            {"activation": resolve_activation(self.activation)},
            name=lname, n_outputs=2)
        _rnn_carry_states(ctx, [(h0, hT)])
        result = out if self.return_sequences else hT
        return result, self.output_type(itype)


@dataclasses.dataclass
class Bidirectional(BaseLayer):
    """Wraps a recurrent layer; runs forward + time-reversed passes and
    merges (reference: nn/conf/layers/recurrent/Bidirectional with Mode
    {CONCAT, ADD, MUL, AVERAGE})."""
    layer: Optional[BaseLayer] = None
    mode: str = "CONCAT"

    def output_type(self, itype):
        inner = self.layer.output_type(itype)
        if self.mode.upper() == "CONCAT":
            if inner.kind == "rnn":
                return InputType.recurrent(2 * inner.dims[0], inner.dims[1])
            return InputType.feed_forward(2 * inner.dims[0])
        return inner

    def build(self, ctx, x, itype):
        lname = ctx.lname("bidir")
        saved_prefix = ctx.prefix
        # distinct parameter namespaces for the two directions
        ctx.prefix = f"{lname}_fwd"
        fwd, inner_t = self.layer.build(ctx, x, itype)
        x_rev = ctx.sd.invoke("reverse", [x], {"axis": (1,)},
                              name=f"{lname}_xrev")
        ctx.prefix = f"{lname}_bwd"
        # the BACKWARD direction must NOT carry TBPTT state across chunks:
        # its "final" state corresponds to the chunk's FIRST timestep, so
        # carrying it into the next chunk injects past, not future, context
        saved_tbptt = ctx.tbptt_batch
        ctx.tbptt_batch = None
        bwd, _ = self.layer.build(ctx, x_rev, itype)
        ctx.tbptt_batch = saved_tbptt
        ctx.prefix = saved_prefix
        if inner_t.kind == "rnn":
            # re-reverse so backward outputs align with forward time order
            bwd = ctx.sd.invoke("reverse", [bwd], {"axis": (1,)},
                                name=f"{lname}_orev")
        mode = self.mode.upper()
        if mode == "CONCAT":
            axis = 2 if inner_t.kind == "rnn" else 1
            out = ctx.sd.invoke("concat", [fwd, bwd], {"axis": axis},
                                name=f"{lname}_out")
        elif mode == "ADD":
            out = fwd.add(bwd, name=f"{lname}_out")
        elif mode == "MUL":
            out = fwd.mul(bwd, name=f"{lname}_out")
        elif mode == "AVERAGE":
            half = ctx.sd.constant(0.5, f"{lname}_half")
            out = fwd.add(bwd).mul(half, name=f"{lname}_out")
        else:
            raise ValueError(f"unknown Bidirectional mode {self.mode}")
        return out, self.output_type(itype)

    def to_json(self) -> dict:
        return {"@class": "Bidirectional", "mode": self.mode,
                "layer": self.layer.to_json()}

    @staticmethod
    def _from_json_fields(d: dict) -> "Bidirectional":
        return Bidirectional(layer=BaseLayer.from_json(d["layer"]),
                             mode=d.get("mode", "CONCAT"))


@dataclasses.dataclass
class LastTimeStepLayer(BaseLayer):
    """Extracts the final timestep of a sequence → FF (reference:
    nn/conf/layers/recurrent/LastTimeStep wrapper semantics, mask-free)."""

    def output_type(self, itype):
        return InputType.feed_forward(itype.dims[0])

    def build(self, ctx, x, itype):
        lname = ctx.lname("laststep")
        t = itype.dims[1]
        if t <= 0:
            raise ValueError("LastTimeStepLayer needs static timesteps")
        out = ctx.sd.invoke(
            "strided_slice", [x],
            {"begin": (0, t - 1, 0), "end": (2**31 - 1, t, 2**31 - 1),
             "strides": (1, 1, 1)}, name=f"{lname}_slice")
        out = out.reshape(-1, itype.dims[0])
        return out, self.output_type(itype)


@dataclasses.dataclass
class RnnOutputLayer(BaseLayer):
    """Per-timestep dense + loss over all timesteps (reference:
    nn/conf/layers/RnnOutputLayer — loss averaged over batch and time)."""
    n_out: int = 0
    loss_function: str = "MCXENT"
    activation: str = "softmax"
    weight_init: str = "XAVIER"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.dims[1])

    def build(self, ctx, x, itype):
        lname = ctx.lname("rnnout")
        n_in = itype.dims[0]
        w = ctx.param(f"{lname}_W", (n_in, self.n_out), self.weight_init)
        z = x.mmul(w, name=f"{lname}_mm")    # (B,T,in)@(in,out) broadcasts
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            z = z.add(b, name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        _attach_loss_head(ctx, z, out, self.loss_function)
        return out, self.output_type(itype)


for _cls in [SimpleRnnLayer, Bidirectional, LastTimeStepLayer,
             RnnOutputLayer]:
    LAYER_TYPES[_cls.__name__] = _cls


@dataclasses.dataclass
class ConvLSTM2DLayer(BaseLayer):
    """Convolutional LSTM over image sequences (Shi et al. 2015; the
    layer Keras calls ConvLSTM2D — reference mapper:
    modelimport/keras/layers/convolutional/KerasConvLSTM2D.java).

    Input: cnn3d (C, T, H, W) with time as the depth axis; output cnn3d
    (F, T, H', W') when return_sequences else cnn (F, H', W'). The
    recurrence is the conv_lstm2d op — one lax.scan, two convs per step.
    """
    n_out: int = 0
    kernel_size: tuple = (3, 3)
    stride: tuple = (1, 1)
    convolution_mode: str = "SAME"
    weight_init: str = "XAVIER"
    forget_gate_bias_init: float = 1.0
    return_sequences: bool = True
    dropout: float = 0.0

    def _spatial_out(self, itype):
        from deeplearning4j_tpu.nn.layers import _as_pair, _conv_out
        c, t, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        return (_conv_out(h, kh, sh, self.convolution_mode),
                _conv_out(w, kw, sw, self.convolution_mode))

    def output_type(self, itype):
        c, t, h, w = itype.dims
        ho, wo = self._spatial_out(itype)
        if self.return_sequences:
            return InputType("cnn3d", (self.n_out, t, ho, wo))
        return InputType("cnn", (self.n_out, ho, wo))

    def build(self, ctx, x, itype):
        from deeplearning4j_tpu.nn.layers import _as_pair, _pad_mode
        if not ctx.cnn_format.endswith("C"):
            raise ValueError("ConvLSTM2DLayer requires channels-last "
                             "runtime layout (cnn_format NHWC)")
        lname = ctx.lname("convlstm")
        c_in = itype.dims[0]
        u = self.n_out
        kh, kw = _as_pair(self.kernel_size)
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w_ih = ctx.param(f"{lname}_Wih", (kh, kw, c_in, 4 * u),
                         self.weight_init)
        w_hh = ctx.param(f"{lname}_Whh", (kh, kw, u, 4 * u),
                         self.weight_init)
        b0 = np.zeros((4 * u,))
        b0[u:2 * u] = self.forget_gate_bias_init   # [i, f, g, o]
        b = ctx.sd.var(f"{lname}_b", value=b0, dtype=ctx.dtype)
        ho, wo = self._spatial_out(itype)
        h0 = ctx.sd.invoke("conv_lstm2d_init_state", [x],
                           {"units": u, "height": ho, "width": wo},
                           name=f"{lname}_h0")
        c0 = ctx.sd.invoke("conv_lstm2d_init_state", [x],
                           {"units": u, "height": ho, "width": wo},
                           name=f"{lname}_c0")
        out, hT, cT = ctx.sd.invoke(
            "conv_lstm2d", [x, h0, c0, w_ih, w_hh, b],
            {"strides": tuple(_as_pair(self.stride)),
             "padding": _pad_mode(self.convolution_mode),
             "return_sequences": self.return_sequences},
            name=lname, n_outputs=3)
        result = out if self.return_sequences else hT
        return result, self.output_type(itype)


LAYER_TYPES[ConvLSTM2DLayer.__name__] = ConvLSTM2DLayer
