"""Layer-based NN framework (reference: deeplearning4j-nn).

Config DSL + MultiLayerNetwork compiled through the SameDiff graph layer —
one execution path, whole-step XLA compilation.
"""
from deeplearning4j_tpu.nn.conf import (
    MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, InputType, LSTMLayer,
    LossLayer, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, ElementWiseVertex,
    DotProductVertex, GraphVertex, L2NormalizeVertex, MergeVertex,
    ScaleVertex, ShiftVertex,
    SubsetVertex)
from deeplearning4j_tpu.nn.conv_layers import (
    Convolution1DLayer, Convolution3DLayer, Cropping2DLayer,
    Deconvolution2DLayer, DepthwiseConvolution2DLayer,
    LocalResponseNormalization, SeparableConvolution2DLayer,
    Subsampling3DLayer, Upsampling2DLayer, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.recurrent_layers import (
    Bidirectional, LastTimeStepLayer, RnnOutputLayer, SimpleRnnLayer)
from deeplearning4j_tpu.nn.layers_ext import (
    CapsuleLayer, CapsuleStrengthLayer, CenterLossOutputLayer, CnnLossLayer,
    Cropping1DLayer, DepthToSpaceLayer, DotProductAttentionLayer,
    ElementWiseMultiplicationLayer, FrozenLayer, GravesLSTMLayer, GRULayer,
    PReLULayer, PrimaryCapsulesLayer, RecurrentAttentionLayer,
    PermuteLayer, RepeatVectorLayer, ReshapeLayer, RnnLossLayer,
    SpaceToDepthLayer, Subsampling1DLayer, Upsampling1DLayer,
    Upsampling3DLayer, VariationalAutoencoderLayer, Yolo2OutputLayer,
    ZeroPadding1DLayer, ZeroPadding3DLayer, Cropping3DLayer)
from deeplearning4j_tpu.nn.noise_layers import (
    AlphaDropoutLayer, GaussianDropoutLayer, GaussianNoiseLayer,
    SpatialDropoutLayer)
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning)
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.activations import resolve_activation

__all__ = [
    "NeuralNetConfiguration", "MultiLayerConfiguration", "MultiLayerNetwork",
    "GaussianNoiseLayer", "GaussianDropoutLayer", "AlphaDropoutLayer",
    "SpatialDropoutLayer", "Cropping3DLayer",
    "ComputationGraph", "ComputationGraphConfiguration", "MergeVertex",
    "ElementWiseVertex", "SubsetVertex", "ScaleVertex", "ShiftVertex",
    "L2NormalizeVertex", "GraphVertex", "DotProductVertex",
    "InputType", "DenseLayer", "ConvolutionLayer", "SubsamplingLayer",
    "BatchNormalization", "ActivationLayer", "DropoutLayer", "EmbeddingLayer",
    "LSTMLayer", "GlobalPoolingLayer", "OutputLayer", "LossLayer",
    "Convolution1DLayer", "Convolution3DLayer", "Subsampling3DLayer",
    "Deconvolution2DLayer", "DepthwiseConvolution2DLayer",
    "SeparableConvolution2DLayer", "LocalResponseNormalization",
    "Upsampling2DLayer", "ZeroPaddingLayer", "Cropping2DLayer",
    "SimpleRnnLayer", "Bidirectional", "LastTimeStepLayer", "RnnOutputLayer",
    "init_weights", "resolve_activation",
]
