"""Attention and transformer layers.

Reference parity: nn/conf/layers/{SelfAttentionLayer,
LearnedSelfAttentionLayer, RecurrentAttentionLayer}.java over the native
fused attention ops (libnd4j generic/nn/multi_head_dot_product_attention
.cpp:34), plus EmbeddingSequenceLayer (nn/conf/layers/
EmbeddingSequenceLayer.java). TransformerEncoderLayer and
PositionalEmbeddingLayer are NEW capability — the reference predates
transformer blocks as first-class layers (SURVEY.md §5); built TPU-first:
(B, T, C) layout, bf16-friendly matmuls, whole-block fusion by XLA, and a
causal flag for LM training.

Sequence layout: (batch, time, features). Token inputs use
InputType kind "ids" with placeholder (batch, time) int32.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nn.layers import (
    BaseLayer, BuildContext, InputType, LAYER_TYPES, _maybe_dropout)


def sequence_ids(timesteps: int) -> InputType:
    """InputType for token-id sequences: placeholder (B, T) int."""
    return InputType("ids", (int(timesteps),))


# patched into InputType for discoverability
InputType.sequence_ids = staticmethod(sequence_ids)

_orig_placeholder_shape = InputType.placeholder_shape


def _placeholder_shape(self):
    if self.kind == "ids":
        return (-1, self.dims[0])
    return _orig_placeholder_shape(self)


InputType.placeholder_shape = _placeholder_shape


@dataclasses.dataclass
class EmbeddingSequenceLayer(BaseLayer):
    """Token ids (B, T) → embeddings (B, T, n_out) (reference:
    nn/conf/layers/EmbeddingSequenceLayer)."""
    n_in: int = 0        # vocabulary
    n_out: int = 0
    weight_init: str = "NORMAL"

    def output_type(self, itype):
        if itype.kind != "ids":
            raise ValueError("EmbeddingSequenceLayer needs "
                             "InputType.sequence_ids(T) input")
        return InputType.recurrent(self.n_out, itype.dims[0])

    def build(self, ctx, x, itype):
        lname = ctx.lname("embedseq")
        self.output_type(itype)
        table = ctx.param(f"{lname}_W", (self.n_in, self.n_out),
                          self.weight_init)
        ids = x.cast("int32")
        out = ctx.sd.invoke("embedding_lookup", [table, ids], {},
                            name=f"{lname}_out")
        return out, self.output_type(itype)


@dataclasses.dataclass
class PositionalEmbeddingLayer(BaseLayer):
    """Adds a learned positional embedding over the time axis (new
    capability; no reference analogue)."""
    max_len: int = 512
    weight_init: str = "NORMAL"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("posemb")
        d = itype.dims[0]
        t = itype.dims[1]
        if t <= 0:
            t = self.max_len
        pos = ctx.param(f"{lname}_P", (t, d), self.weight_init)
        out = x.add(pos, name=f"{lname}_out")   # broadcasts over batch
        return out, itype


@dataclasses.dataclass
class SelfAttentionLayer(BaseLayer):
    """Multi-head self-attention projecting to n_out (reference:
    nn/conf/layers/SelfAttentionLayer over native MHA op)."""
    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.dims[1])

    def _head_size(self):
        if self.head_size:
            return self.head_size
        if self.n_out % self.n_heads:
            raise ValueError("n_out must divide by n_heads (or set head_size)")
        return self.n_out // self.n_heads

    def build(self, ctx, x, itype):
        lname = ctx.lname("selfattn")
        d_in = itype.dims[0]
        hk = self._head_size() * self.n_heads
        wq = ctx.param(f"{lname}_Wq", (d_in, hk), self.weight_init)
        wk = ctx.param(f"{lname}_Wk", (d_in, hk), self.weight_init)
        wv = ctx.param(f"{lname}_Wv", (d_in, hk), self.weight_init)
        wo = ctx.param(f"{lname}_Wo", (hk, self.n_out), self.weight_init)
        out = ctx.sd.invoke(
            "multi_head_dot_product_attention", [x, x, x, wq, wk, wv, wo],
            {"nheads": self.n_heads}, name=f"{lname}_out")
        return out, self.output_type(itype)


@dataclasses.dataclass
class LearnedSelfAttentionLayer(BaseLayer):
    """Attention with N learned query vectors → fixed-length output
    (B, n_queries, n_out) (reference: LearnedSelfAttentionLayer)."""
    n_out: int = 0
    n_heads: int = 1
    n_queries: int = 1
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, self.n_queries)

    def build(self, ctx, x, itype):
        lname = ctx.lname("learnedattn")
        d_in = itype.dims[0]
        if self.n_out % self.n_heads:
            raise ValueError("n_out must divide by n_heads")
        hk = self.n_out
        q = ctx.param(f"{lname}_Q", (self.n_queries, d_in), self.weight_init)
        wq = ctx.param(f"{lname}_Wq", (d_in, hk), self.weight_init)
        wk = ctx.param(f"{lname}_Wk", (d_in, hk), self.weight_init)
        wv = ctx.param(f"{lname}_Wv", (d_in, hk), self.weight_init)
        wo = ctx.param(f"{lname}_Wo", (hk, self.n_out), self.weight_init)
        # broadcast the learned queries over the batch: zeros (B,nq,d) + Q
        zeros = ctx.sd.invoke("rnn_init_state", [x],
                              {"units": self.n_queries * d_in},
                              name=f"{lname}_z")
        zeros = zeros.reshape(-1, self.n_queries, d_in)
        qb = zeros.add(q, name=f"{lname}_qb")
        out = ctx.sd.invoke(
            "multi_head_dot_product_attention", [qb, x, x, wq, wk, wv, wo],
            {"nheads": self.n_heads}, name=f"{lname}_out")
        return out, self.output_type(itype)


@dataclasses.dataclass
class LayerNormLayer(BaseLayer):
    """Layer normalization over the feature axis (native layer_norm op;
    the reference exposes layernorm only as an op — first-class layer is
    transformer-era capability)."""
    eps: float = 1e-5

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("ln")
        d = itype.dims[0]
        gamma = ctx.sd.var(f"{lname}_g", value=np.ones((d,)), dtype=ctx.dtype)
        beta = ctx.sd.var(f"{lname}_b", value=np.zeros((d,)), dtype=ctx.dtype)
        out = ctx.sd.invoke("layer_norm", [x, gamma, beta],
                            {"axis": -1, "epsilon": self.eps}, name=lname)
        return out, itype


@dataclasses.dataclass
class TransformerEncoderLayer(BaseLayer):
    """Pre-LN transformer block: x + MHA(LN(x)); x + FFN(LN(x)).

    New capability (no reference analogue). ``causal=True`` masks future
    positions for LM training.
    """
    n_heads: int = 4
    d_ff: int = 0            # default 4*d_model
    drop_prob: float = 0.0   # DROP probability (unlike the retain-prob
                             # `dropout` field on DL4J-parity layers)
    causal: bool = False
    activation: str = "gelu"
    weight_init: str = "XAVIER"
    eps: float = 1e-5

    def output_type(self, itype):
        return itype

    def _ln(self, ctx, x, d, name):
        gamma = ctx.sd.var(f"{name}_g", value=np.ones((d,)), dtype=ctx.dtype)
        beta = ctx.sd.var(f"{name}_b", value=np.zeros((d,)), dtype=ctx.dtype)
        return ctx.sd.invoke("layer_norm", [x, gamma, beta],
                             {"axis": -1, "epsilon": self.eps}, name=name)

    def build(self, ctx, x, itype):
        from deeplearning4j_tpu.nn.activations import apply_activation
        lname = ctx.lname("encoder")
        d = itype.dims[0]
        t = itype.dims[1]
        d_ff = self.d_ff or 4 * d
        if d % self.n_heads:
            raise ValueError("d_model must divide by n_heads")

        # --- attention sublayer (pre-LN) -------------------------------
        h = self._ln(ctx, x, d, f"{lname}_ln1")
        wq = ctx.param(f"{lname}_Wq", (d, d), self.weight_init)
        wk = ctx.param(f"{lname}_Wk", (d, d), self.weight_init)
        wv = ctx.param(f"{lname}_Wv", (d, d), self.weight_init)
        wo = ctx.param(f"{lname}_Wo", (d, d), self.weight_init)
        attrs = {"nheads": self.n_heads}
        if self.causal:
            if t <= 0:
                raise ValueError("causal encoder needs a static timestep "
                                 "count in the InputType")
            mask = np.tril(np.ones((t, t), np.float32))[None, None]
            cmask = ctx.sd.constant(mask, f"{lname}_mask")
            attrs["mask"] = None  # mask passed as input below
            attn = ctx.sd.invoke(
                "multi_head_dot_product_attention",
                [h, h, h, wq, wk, wv, wo, cmask], {"nheads": self.n_heads},
                name=f"{lname}_mha")
        else:
            attn = ctx.sd.invoke(
                "multi_head_dot_product_attention",
                [h, h, h, wq, wk, wv, wo], attrs, name=f"{lname}_mha")
        if self.drop_prob and ctx.training:
            attn = ctx.sd.invoke("dropout", [attn], {"p": 1.0 - self.drop_prob},
                                 name=f"{lname}_adrop")
        x = x.add(attn, name=f"{lname}_res1")

        # --- feed-forward sublayer (pre-LN) ----------------------------
        h2 = self._ln(ctx, x, d, f"{lname}_ln2")
        w1 = ctx.param(f"{lname}_Wff1", (d, d_ff), self.weight_init)
        b1 = ctx.sd.var(f"{lname}_bff1", value=np.zeros((d_ff,)),
                        dtype=ctx.dtype)
        w2 = ctx.param(f"{lname}_Wff2", (d_ff, d), self.weight_init)
        b2 = ctx.sd.var(f"{lname}_bff2", value=np.zeros((d,)),
                        dtype=ctx.dtype)
        ff = h2.mmul(w1).add(b1)
        ff = apply_activation(ctx.sd, ff, self.activation, f"{lname}_ffact")
        ff = ff.mmul(w2).add(b2)
        if self.drop_prob and ctx.training:
            ff = ctx.sd.invoke("dropout", [ff], {"p": 1.0 - self.drop_prob},
                               name=f"{lname}_fdrop")
        out = x.add(ff, name=f"{lname}_out")
        return out, itype


@dataclasses.dataclass
class MultiHeadAttentionLayer(BaseLayer):
    """Self-attention with per-projection biases — the keras
    MultiHeadAttention-compatible form (separate head_size; output width
    independent of d_model). Reference analogue:
    multi_head_dot_product_attention.cpp:34 (which has no biases; this
    layer adds them for import fidelity)."""
    n_heads: int = 4
    head_size: int = 0        # dk; default d_model // n_heads
    n_out: int = 0            # output width; default d_model
    has_bias: bool = True
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        d = self.n_out or itype.dims[0]
        return InputType.recurrent(d, itype.dims[1])

    def build(self, ctx, x, itype):
        lname = ctx.lname("mha")
        d = itype.dims[0]
        h = self.n_heads
        dk = self.head_size or d // h
        d_out = self.n_out or d

        def proj(nm, w_shape, b_shape, src):
            w = ctx.param(f"{lname}_W{nm}", w_shape, self.weight_init)
            z = ctx.sd.invoke("einsum", [src, w],
                              {"equation": "btc,cd->btd"},
                              name=f"{lname}_{nm}")
            if self.has_bias:
                b = ctx.sd.var(f"{lname}_b{nm}", value=np.zeros(b_shape),
                               dtype=ctx.dtype)
                z = z.add(b, name=f"{lname}_{nm}b")
            return z

        q = proj("q", (d, h * dk), (h * dk,), x)
        k = proj("k", (d, h * dk), (h * dk,), x)
        v = proj("v", (d, h * dk), (h * dk,), x)

        t_static = itype.dims[1]
        if t_static <= 0:
            raise ValueError("MultiHeadAttentionLayer needs static "
                             "timesteps in the InputType")

        def heads(t, nm):
            r = ctx.sd.invoke("reshape", [t],
                              {"shape": (-1, t_static, h, dk)},
                              name=f"{lname}_{nm}r")
            return ctx.sd.invoke("permute", [r], {"axes": (0, 2, 1, 3)},
                                 name=f"{lname}_{nm}h")
        qh, kh, vh = heads(q, "q"), heads(k, "k"), heads(v, "v")
        att = ctx.sd.invoke("dot_product_attention", [qh, kh, vh], {},
                            name=f"{lname}_att")
        merged = ctx.sd.invoke("permute", [att], {"axes": (0, 2, 1, 3)},
                               name=f"{lname}_mrg")
        merged = ctx.sd.invoke("reshape", [merged],
                               {"shape": (-1, t_static, h * dk)},
                               name=f"{lname}_flat")
        wo = ctx.param(f"{lname}_Wo", (h * dk, d_out), self.weight_init)
        out = ctx.sd.invoke("einsum", [merged, wo],
                            {"equation": "btc,cd->btd"}, name=f"{lname}_o")
        if self.has_bias:
            bo = ctx.sd.var(f"{lname}_bo", value=np.zeros(d_out),
                            dtype=ctx.dtype)
            out = out.add(bo, name=lname)
        return out, self.output_type(itype)


for _cls in [EmbeddingSequenceLayer, PositionalEmbeddingLayer,
             SelfAttentionLayer, LearnedSelfAttentionLayer, LayerNormLayer,
             TransformerEncoderLayer, MultiHeadAttentionLayer]:
    LAYER_TYPES[_cls.__name__] = _cls
