"""Layer breadth wave 2: VAE, object detection, capsules, attention,
peephole recurrence, and structural layers.

Reference parity (deeplearning4j-nn nn/conf/layers unless noted):
- VariationalAutoencoderLayer: variational/VariationalAutoencoder.java —
  encoder/decoder MLPs, reparameterized latent, ELBO (reconstruction +
  KL) as an unsupervised loss contribution.
- Yolo2OutputLayer: objdetect/Yolo2OutputLayer.java (+ util NMS through
  the image ops / nn/objdetect.py helpers).
- CapsuleLayer / PrimaryCapsulesLayer / CapsuleStrengthLayer:
  CapsuleLayer.java trio (Sabour et al. routing).
- DotProductAttentionLayer / RecurrentAttentionLayer: the attention layer
  family (RecurrentAttentionLayer.java; dot_product_attention native op).
- GravesLSTMLayer: GravesLSTM.java (peephole LSTM).
- GRULayer: recurrent GRU (nd4j gruCell / libnd4j gruCell.cpp).
- structural: RepeatVector, PReLU, ElementWiseMultiplicationLayer,
  Subsampling1DLayer, ZeroPadding1D/3D, Cropping1D, Upsampling1D/3D,
  SpaceToDepth/DepthToSpace, CnnLossLayer, RnnLossLayer,
  CenterLossOutputLayer, FrozenLayer (+FrozenLayerWithBackprop alias
  semantics), MaskZeroLayer omitted (masking arrives with padded-batch
  support).

All layers compile through the same SameDiff path; losses attach by
mark_as_loss so multiple heads/aux losses sum (reference:
multiple-output ComputationGraph loss accumulation).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.layers import (
    BaseLayer, InputType, LAYER_TYPES, _as_pair, _conv_out, _pad_mode)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class VariationalAutoencoderLayer(BaseLayer):
    """VAE pretrain layer (reference: variational/
    VariationalAutoencoder.java). Output = latent (mean at inference,
    reparameterized sample in training); training adds the negative ELBO
    (reconstruction + kl_weight * KL) as a loss contribution."""
    n_out: int = 0                       # latent size
    encoder_layer_sizes: Tuple[int, ...] = (256,)
    decoder_layer_sizes: Tuple[int, ...] = (256,)
    activation: str = "relu"
    # 'gaussian' -> MSE reconstruction; 'bernoulli' -> sigmoid BCE
    reconstruction_distribution: str = "gaussian"
    kl_weight: float = 1.0
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def _mlp(self, ctx, lname, x, n_in, sizes):
        cur, width = x, n_in
        for i, h in enumerate(sizes):
            w = ctx.param(f"{lname}_W{i}", (width, h), self.weight_init)
            b = ctx.sd.var(f"{lname}_b{i}", value=np.zeros(h),
                           dtype=ctx.dtype)
            cur = apply_activation(ctx.sd, cur.mmul(w).add(b),
                                   self.activation, f"{lname}_h{i}")
            width = h
        return cur, width

    def build(self, ctx, x, itype):
        lname = ctx.lname("vae")
        n_in = itype.flat_size
        enc, width = self._mlp(ctx, f"{lname}_enc", x, n_in,
                               self.encoder_layer_sizes)
        w_mu = ctx.param(f"{lname}_Wmu", (width, self.n_out),
                         self.weight_init)
        b_mu = ctx.sd.var(f"{lname}_bmu", value=np.zeros(self.n_out),
                          dtype=ctx.dtype)
        w_lv = ctx.param(f"{lname}_Wlv", (width, self.n_out),
                         self.weight_init)
        b_lv = ctx.sd.var(f"{lname}_blv", value=np.zeros(self.n_out),
                          dtype=ctx.dtype)
        mean = enc.mmul(w_mu).add(b_mu, name=f"{lname}_mean")
        logvar = enc.mmul(w_lv).add(b_lv, name=f"{lname}_logvar")
        if ctx.training:
            # z = mean + exp(logvar/2) * eps via noise on a zero tensor
            std = ctx.sd.invoke("exp", [logvar.mul(0.5)], {},
                                name=f"{lname}_std")
            eps = ctx.sd.invoke(
                "gaussian_noise", [mean.mul(0.0)], {"stddev": 1.0},
                name=f"{lname}_eps")
            z = mean.add(std.mul(eps), name=f"{lname}_z")
            # decoder + ELBO
            dec, dwidth = self._mlp(ctx, f"{lname}_dec", z, self.n_out,
                                    self.decoder_layer_sizes)
            w_r = ctx.param(f"{lname}_Wrec", (dwidth, n_in),
                            self.weight_init)
            b_r = ctx.sd.var(f"{lname}_brec", value=np.zeros(n_in),
                             dtype=ctx.dtype)
            recon_logits = dec.mmul(w_r).add(b_r, name=f"{lname}_rec")
            if self.reconstruction_distribution == "bernoulli":
                recon = ctx.sd.invoke("sigm_cross_entropy",
                                      [recon_logits, x], {},
                                      name=f"{lname}_recon_loss")
            else:
                recon = ctx.sd.invoke("mean_sqerr_loss", [recon_logits, x],
                                      {}, name=f"{lname}_recon_loss")
            # KL(q(z|x) || N(0,I)) = -0.5 mean(1 + lv - mu^2 - e^lv)
            kl_terms = logvar.add(1.0).sub(mean.square()).sub(
                ctx.sd.invoke("exp", [logvar], {}, name=f"{lname}_elv"))
            kl = kl_terms.mean().mul(-0.5, name=f"{lname}_kl")
            elbo = recon.add(kl.mul(self.kl_weight), name=f"{lname}_elbo")
            elbo.mark_as_loss()
            return z, self.output_type(itype)
        return mean, self.output_type(itype)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Yolo2OutputLayer(BaseLayer):
    """YOLOv2 detection head (reference: objdetect/Yolo2OutputLayer.java).

    Input: cnn feature map with A*(5+C) channels on an (H, W) grid.
    Labels: (B, 4+C, H, W) — corner bbox in grid units + class one-hot.
    Output passes the raw grid through (decode with nn/objdetect.py).
    """
    anchors: Tuple[float, ...] = (1.0, 1.0)    # flat (w,h) pairs
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5
    # graph builds create a labels placeholder for this head even though
    # it exposes no loss_function attribute (labels are the target grid)
    consumes_labels = True

    def output_type(self, itype):
        return itype

    def labels_placeholder_shape(self, otype):
        """The declared labels layout is (B, 4+C, H, W) — bbox corners
        + class one-hot — NOT the A*(5+C) prediction grid the generic
        labels-shaped-like-output fallback would declare (the wrong
        declaration was caught by the static analyzer: yolo2_loss
        cannot compose a (…, A*(5+C)) labels tensor)."""
        c, h, w = otype.dims
        n_anchors = max(1, len(self.anchors) // 2)
        n_classes = c // n_anchors - 5
        return (-1, 4 + n_classes, h, w)

    def build(self, ctx, x, itype):
        lname = ctx.lname("yolo2")
        c, h, w = itype.dims
        n_anchors = len(self.anchors) // 2
        if c % n_anchors:
            raise ValueError(f"channels {c} not divisible by "
                             f"{n_anchors} anchors")
        # labels arrive NCHW (external contract); the runtime tensor is
        # ctx.cnn_format. yolo2_loss wants channels-last for both.
        labels = ctx.labels_var
        if labels is not None and ctx.training:
            lab_nhwc = ctx.sd.invoke("permute", [labels],
                                     {"axes": (0, 2, 3, 1)},
                                     name=f"{lname}_lab_nhwc")
            pred = x if ctx.cnn_format == "NHWC" else ctx.sd.invoke(
                "permute", [x], {"axes": (0, 2, 3, 1)},
                name=f"{lname}_pred_nhwc")
            loss = ctx.sd.invoke(
                "yolo2_loss", [pred, lab_nhwc],
                {"anchors": tuple(self.anchors),
                 "lambda_coord": self.lambda_coord,
                 "lambda_noobj": self.lambda_noobj}, name=f"{lname}_loss")
            loss.mark_as_loss()
            ctx.loss_var = loss
        ctx.output_var = x
        return x, itype


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class PrimaryCapsulesLayer(BaseLayer):
    """Conv -> capsule groups -> squash (reference: PrimaryCapsules.java)."""
    capsules: int = 8                 # capsule channel groups
    capsule_dimensions: int = 8
    kernel_size: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)
    weight_init: str = "RELU"

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        oh = _conv_out(h, kh, sh, "VALID")
        ow = _conv_out(w, kw, sw, "VALID")
        n_caps = self.capsules * oh * ow
        return InputType("caps", (n_caps, self.capsule_dimensions))

    def build(self, ctx, x, itype):
        lname = ctx.lname("primcaps")
        c_in = itype.dims[0]
        kh, kw = _as_pair(self.kernel_size)
        n_out = self.capsules * self.capsule_dimensions
        w = ctx.param(f"{lname}_W", (kh, kw, c_in, n_out), self.weight_init)
        z = ctx.sd.invoke("conv2d", [x, w],
                          {"strides": _as_pair(self.stride),
                           "padding": "VALID",
                           "data_format": ctx.cnn_format},
                          name=f"{lname}_conv")
        if ctx.cnn_format != "NHWC":
            # capsule vectors are contiguous groups of the CHANNEL axis;
            # bring channels last before grouping
            z = ctx.sd.invoke("permute", [z], {"axes": (0, 2, 3, 1)},
                              name=f"{lname}_cl")
        otype = self.output_type(itype)
        n_caps, d = otype.dims
        z = ctx.sd.invoke("reshape", [z], {"shape": (-1, n_caps, d)},
                          name=f"{lname}_caps")
        out = ctx.sd.invoke("capsule_squash", [z], {},
                            name=f"{lname}_squash")
        return out, otype


@dataclasses.dataclass
class CapsuleLayer(BaseLayer):
    """Dynamic-routing capsules (reference: CapsuleLayer.java)."""
    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType("caps", (self.capsules, self.capsule_dimensions))

    def build(self, ctx, x, itype):
        lname = ctx.lname("caps")
        n_in, d_in = itype.dims
        w = ctx.param(f"{lname}_W",
                      (n_in, self.capsules, d_in, self.capsule_dimensions),
                      self.weight_init)
        out = ctx.sd.invoke(
            "capsule_routing", [x, w],
            {"n_capsules": self.capsules,
             "capsule_dim": self.capsule_dimensions,
             "routings": self.routings}, name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class CapsuleStrengthLayer(BaseLayer):
    """Capsule vector norms -> class scores (reference:
    CapsuleStrengthLayer.java)."""

    def output_type(self, itype):
        return InputType.feed_forward(itype.dims[0])

    def build(self, ctx, x, itype):
        lname = ctx.lname("capstrength")
        sq = ctx.sd.invoke("reduce_sum",
                           [ctx.sd.invoke("square", [x], {},
                                          name=f"{lname}_sq")],
                           {"axis": (2,)}, name=f"{lname}_sum")
        out = ctx.sd.invoke("sqrt", [sq], {}, name=lname)
        return out, self.output_type(itype)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class DotProductAttentionLayer(BaseLayer):
    """Scaled dot-product attention over a sequence with learned Q/K/V
    projections (reference: the dot_product_attention native op family +
    attention layer configs; multi-head when n_heads > 1)."""
    n_out: int = 0
    n_heads: int = 1
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.dims[1])

    def build(self, ctx, x, itype):
        lname = ctx.lname("dpattn")
        n_in = itype.dims[0]
        if self.n_out % self.n_heads:
            raise ValueError("n_out must divide by n_heads")
        wq = ctx.param(f"{lname}_Wq", (n_in, self.n_out), self.weight_init)
        wk = ctx.param(f"{lname}_Wk", (n_in, self.n_out), self.weight_init)
        wv = ctx.param(f"{lname}_Wv", (n_in, self.n_out), self.weight_init)
        wo = ctx.param(f"{lname}_Wo", (self.n_out, self.n_out),
                       self.weight_init)
        out = ctx.sd.invoke(
            "multi_head_dot_product_attention", [x, x, x, wq, wk, wv, wo],
            {"nheads": self.n_heads}, name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class RecurrentAttentionLayer(BaseLayer):
    """Recurrent cell with per-step attention over the full input sequence
    (reference: RecurrentAttentionLayer.java — r_t combines the recurrent
    state with an attention readout where the query is the current step)."""
    n_out: int = 0
    weight_init: str = "XAVIER"
    activation: str = "tanh"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.dims[1])

    def build(self, ctx, x, itype):
        lname = ctx.lname("recattn")
        n_in = itype.dims[0]
        wq = ctx.param(f"{lname}_Wq", (n_in, n_in), self.weight_init)
        w_ih = ctx.param(f"{lname}_W", (2 * n_in, self.n_out),
                         self.weight_init)
        w_hh = ctx.param(f"{lname}_Wr", (self.n_out, self.n_out),
                         self.weight_init)
        b = ctx.sd.var(f"{lname}_b", value=np.zeros(self.n_out),
                       dtype=ctx.dtype)
        # attention readout per step: q = x W_q, attn = softmax(q k^T) v
        # with k = v = x (single-head dot-product attention)
        q = ctx.sd.invoke("einsum", [x, wq], {"equation": "btc,cd->btd"},
                          name=f"{lname}_q")
        attn = ctx.sd.invoke("dot_product_attention", [q, x, x], {},
                             name=f"{lname}_attn")
        cat = ctx.sd.invoke("concat", [x, attn], {"axis": -1},
                            name=f"{lname}_cat")
        h0 = ctx.sd.invoke("rnn_init_state", [cat], {"units": self.n_out},
                           name=f"{lname}_h0")
        out, _ = ctx.sd.invoke(
            "simple_rnn_layer", [cat, h0, w_ih, w_hh, b],
            {"activation": self.activation}, name=lname, n_outputs=2)
        return out, self.output_type(itype)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class GravesLSTMLayer(BaseLayer):
    """Peephole LSTM (reference: GravesLSTM.java)."""
    n_out: int = 0
    weight_init: str = "XAVIER"
    forget_gate_bias_init: float = 1.0
    return_sequences: bool = True

    def output_type(self, itype):
        if self.return_sequences:
            return InputType.recurrent(self.n_out, itype.dims[1])
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("glstm")
        n_in, u = itype.dims[0], self.n_out
        w_ih = ctx.param(f"{lname}_Wih", (n_in, 4 * u), self.weight_init)
        w_hh = ctx.param(f"{lname}_Whh", (u, 4 * u), self.weight_init)
        w_p = ctx.sd.var(f"{lname}_Wp", value=np.zeros((3, u)),
                         dtype=ctx.dtype)
        b0 = np.zeros((4 * u,))
        b0[u:2 * u] = self.forget_gate_bias_init
        b = ctx.sd.var(f"{lname}_b", value=b0, dtype=ctx.dtype)
        from deeplearning4j_tpu.nn.layers import (_rnn_carry_states,
                                                  _rnn_initial_states)
        h0, c0 = _rnn_initial_states(ctx, lname, x, u, ("h0", "c0"))
        out, hT, cT = ctx.sd.invoke(
            "graves_lstm_layer", [x, h0, c0, w_ih, w_hh, w_p, b],
            {"return_sequences": self.return_sequences}, name=lname,
            n_outputs=3)
        _rnn_carry_states(ctx, [(h0, hT), (c0, cT)])
        return (out if self.return_sequences else hT), \
            self.output_type(itype)


@dataclasses.dataclass
class GRULayer(BaseLayer):
    """GRU over sequences (reference: nd4j gruCell, libnd4j gruCell.cpp)."""
    n_out: int = 0
    weight_init: str = "XAVIER"
    return_sequences: bool = True

    def output_type(self, itype):
        if self.return_sequences:
            return InputType.recurrent(self.n_out, itype.dims[1])
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("gru")
        n_in, u = itype.dims[0], self.n_out
        w_ih = ctx.param(f"{lname}_Wih", (n_in, 3 * u), self.weight_init)
        w_hh = ctx.param(f"{lname}_Whh", (u, 3 * u), self.weight_init)
        b_ih = ctx.sd.var(f"{lname}_bih", value=np.zeros(3 * u),
                          dtype=ctx.dtype)
        b_hh = ctx.sd.var(f"{lname}_bhh", value=np.zeros(3 * u),
                          dtype=ctx.dtype)
        from deeplearning4j_tpu.nn.layers import (_rnn_carry_states,
                                                  _rnn_initial_states)
        h0, = _rnn_initial_states(ctx, lname, x, u)
        out, hT = ctx.sd.invoke("gru_layer", [x, h0, w_ih, w_hh, b_ih, b_hh],
                                {}, name=lname, n_outputs=2)
        _rnn_carry_states(ctx, [(h0, hT)])
        return (out if self.return_sequences else hT), \
            self.output_type(itype)


# ---------------------------------------------------------------------------
# structural layers
@dataclasses.dataclass
class RepeatVectorLayer(BaseLayer):
    """(B, n) -> (B, T, n) (reference: misc/RepeatVector.java)."""
    n: int = 1

    def output_type(self, itype):
        return InputType.recurrent(itype.dims[0], self.n)

    def build(self, ctx, x, itype):
        lname = ctx.lname("repeat")
        x2 = ctx.sd.invoke("expand_dims", [x], {"axis": 1},
                           name=f"{lname}_e")
        out = ctx.sd.invoke("tile", [x2], {"reps": (1, self.n, 1)},
                            name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class PReLULayer(BaseLayer):
    """Learned leaky slope (reference: PReLULayer.java; per-feature
    alpha)."""
    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("prelu")
        # feature count is dims[0] for every InputType kind (rnn dims are
        # (features, timesteps) even though the runtime tensor is (B, T, C))
        n = itype.dims[0]
        if itype.kind == "cnn" and ctx.cnn_format == "NHWC":
            shape = (1, 1, 1, n)
        elif itype.kind == "cnn":
            shape = (1, n, 1, 1)
        elif itype.kind == "rnn":
            shape = (1, 1, n)
        else:
            shape = (1, n)
        alpha = ctx.sd.var(f"{lname}_alpha", value=np.full(shape, 0.25),
                           dtype=ctx.dtype)
        out = ctx.sd.invoke("prelu", [x, alpha], {}, name=lname)
        return out, itype


@dataclasses.dataclass
class ElementWiseMultiplicationLayer(BaseLayer):
    """out = activation(w * x + b) elementwise (reference:
    misc/ElementWiseMultiplicationLayer.java)."""
    activation: str = "identity"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("ewmul")
        n = itype.dims[0]
        w = ctx.sd.var(f"{lname}_W", value=np.ones(n), dtype=ctx.dtype)
        b = ctx.sd.var(f"{lname}_b", value=np.zeros(n), dtype=ctx.dtype)
        out = apply_activation(ctx.sd, x.mul(w).add(b), self.activation,
                               lname)
        return out, itype


@dataclasses.dataclass
class Subsampling1DLayer(BaseLayer):
    """1D pooling over (B, T, C) (reference: Subsampling1DLayer.java)."""
    pooling_type: str = "MAX"
    kernel_size: int = 2
    stride: Optional[int] = None
    convolution_mode: str = "VALID"

    def output_type(self, itype):
        c, t = itype.dims
        s = self.stride or self.kernel_size
        return InputType.recurrent(
            c, _conv_out(t, self.kernel_size, s, self.convolution_mode)
            if t > 0 else t)

    def build(self, ctx, x, itype):
        lname = ctx.lname("pool1d")
        # (B, T, C) -> (B, T, 1, C): reuse the 2d pool in NHWC
        x4 = ctx.sd.invoke("expand_dims", [x], {"axis": 2},
                           name=f"{lname}_e")
        op = {"MAX": "max_pool2d", "AVG": "avg_pool2d"}[
            self.pooling_type.upper()]
        z = ctx.sd.invoke(op, [x4], {
            "kernel": (self.kernel_size, 1),
            "strides": (self.stride or self.kernel_size, 1),
            "padding": _pad_mode(self.convolution_mode),
            "data_format": "NHWC"}, name=f"{lname}_p")
        out = ctx.sd.invoke("squeeze", [z], {"axis": (2,)}, name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class ZeroPadding1DLayer(BaseLayer):
    """(reference: ZeroPadding1DLayer.java) padding=(left, right) on T."""
    padding: Tuple[int, int] = (1, 1)

    def output_type(self, itype):
        c, t = itype.dims
        return InputType.recurrent(c, t + sum(self.padding) if t > 0 else t)

    def build(self, ctx, x, itype):
        l, r = self.padding
        out = ctx.sd.invoke("pad", [x],
                            {"paddings": ((0, 0), (l, r), (0, 0))},
                            name=ctx.lname("zeropad1d"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class Cropping1DLayer(BaseLayer):
    """(reference: convolutional/Cropping1D.java)."""
    cropping: Tuple[int, int] = (0, 0)

    def output_type(self, itype):
        c, t = itype.dims
        return InputType.recurrent(c, t - sum(self.cropping) if t > 0 else t)

    def build(self, ctx, x, itype):
        l, r = self.cropping
        t = itype.dims[1]
        big = 2 ** 31 - 1
        # timesteps may be unknown (-1): use a negative python-slice end
        end_t = t - r if t > 0 else (big if r == 0 else -r)
        out = ctx.sd.invoke("strided_slice", [x],
                            {"begin": (0, l, 0),
                             "end": (big, end_t, big),
                             "strides": (1, 1, 1)},
                            name=ctx.lname("crop1d"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class Upsampling1DLayer(BaseLayer):
    """(reference: Upsampling1D.java): repeat timesteps."""
    size: int = 2

    def output_type(self, itype):
        c, t = itype.dims
        return InputType.recurrent(c, t * self.size if t > 0 else t)

    def build(self, ctx, x, itype):
        out = ctx.sd.invoke("repeat", [x],
                            {"repeats": self.size, "axis": 1},
                            name=ctx.lname("upsample1d"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class Upsampling3DLayer(BaseLayer):
    """(reference: Upsampling3D.java): nearest-neighbour volume scale."""
    size: Tuple[int, int, int] = (2, 2, 2)

    def output_type(self, itype):
        c, d, h, w = itype.dims
        fd, fh, fw = self.size
        return InputType("cnn3d", (c, d * fd, h * fh, w * fw))

    def build(self, ctx, x, itype):
        lname = ctx.lname("upsample3d")
        # channels-last runtime: (B, D, H, W, C); NCDHW otherwise
        axes = (1, 2, 3) if ctx.cnn_format == "NHWC" else (2, 3, 4)
        out = x
        for ax, f in zip(axes, self.size):
            if f > 1:
                out = ctx.sd.invoke("repeat", [out],
                                    {"repeats": f, "axis": ax},
                                    name=f"{lname}_ax{ax}")
        return out, self.output_type(itype)


@dataclasses.dataclass
class ZeroPadding3DLayer(BaseLayer):
    """(reference: ZeroPadding3DLayer.java) padding=(d0,d1,h0,h1,w0,w1)."""
    padding: Tuple[int, int, int, int, int, int] = (1, 1, 1, 1, 1, 1)

    def output_type(self, itype):
        c, d, h, w = itype.dims
        p = self.padding
        return InputType("cnn3d", (c, d + p[0] + p[1], h + p[2] + p[3],
                                   w + p[4] + p[5]))

    def build(self, ctx, x, itype):
        p = self.padding
        spatial = ((p[0], p[1]), (p[2], p[3]), (p[4], p[5]))
        if ctx.cnn_format == "NHWC":
            pads = ((0, 0),) + spatial + ((0, 0),)
        else:
            pads = ((0, 0), (0, 0)) + spatial
        out = ctx.sd.invoke("pad", [x], {"paddings": pads},
                            name=ctx.lname("zeropad3d"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class SpaceToDepthLayer(BaseLayer):
    """(reference: SpaceToDepthLayer.java)."""
    block_size: int = 2

    def output_type(self, itype):
        c, h, w = itype.dims
        b = self.block_size
        return InputType("cnn", (c * b * b, h // b, w // b))

    def build(self, ctx, x, itype):
        out = ctx.sd.invoke("space_to_depth", [x],
                            {"block_size": self.block_size,
                             "data_format": ctx.cnn_format},
                            name=ctx.lname("s2d"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class DepthToSpaceLayer(BaseLayer):
    """(reference: the depth_to_space op / SpaceToDepth inverse)."""
    block_size: int = 2

    def output_type(self, itype):
        c, h, w = itype.dims
        b = self.block_size
        return InputType("cnn", (c // (b * b), h * b, w * b))

    def build(self, ctx, x, itype):
        out = ctx.sd.invoke("depth_to_space", [x],
                            {"block_size": self.block_size,
                             "data_format": ctx.cnn_format},
                            name=ctx.lname("d2s"))
        return out, self.output_type(itype)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class CnnLossLayer(BaseLayer):
    """Per-pixel loss on a cnn map (reference: CnnLossLayer.java);
    labels NCHW like the output contract."""
    loss_function: str = "MSE"
    activation: str = "identity"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        from deeplearning4j_tpu.nn.layers import (_FUSED_LOGIT_LOSSES,
                                                  _LOSS_OPS)
        lname = ctx.lname("cnnloss")
        out = apply_activation(ctx.sd, x, self.activation, f"{lname}_act")
        labels = ctx.labels_var
        if labels is not None:
            lab = labels
            if ctx.cnn_format == "NHWC":
                lab = ctx.sd.invoke("permute", [labels],
                                    {"axes": (0, 2, 3, 1)},
                                    name=f"{lname}_lab")
            loss_op = _LOSS_OPS[self.loss_function.upper()]
            loss_in = x if loss_op in _FUSED_LOGIT_LOSSES else out
            loss = ctx.sd.invoke(loss_op, [loss_in, lab], {},
                                 name=f"{lname}_loss")
            loss.mark_as_loss()
            ctx.loss_var = loss
        ctx.output_var = out
        return out, itype


@dataclasses.dataclass
class RnnLossLayer(BaseLayer):
    """Per-timestep loss (reference: RnnLossLayer.java)."""
    loss_function: str = "MCXENT"
    activation: str = "softmax"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        from deeplearning4j_tpu.nn.layers import (_FUSED_LOGIT_LOSSES,
                                                  _LOSS_OPS)
        lname = ctx.lname("rnnloss")
        out = apply_activation(ctx.sd, x, self.activation, f"{lname}_act")
        labels = ctx.labels_var
        if labels is not None:
            loss_op = _LOSS_OPS[self.loss_function.upper()]
            loss_in = x if loss_op in _FUSED_LOGIT_LOSSES else out
            loss = ctx.sd.invoke(loss_op, [loss_in, labels], {},
                                 name=f"{lname}_loss")
            loss.mark_as_loss()
            ctx.loss_var = loss
        ctx.output_var = out
        return out, itype


@dataclasses.dataclass
class CenterLossOutputLayer(BaseLayer):
    """Softmax head + center loss (reference:
    CenterLossOutputLayer.java — per-class feature centers pulled toward
    their class's embeddings; centers update as non-trainable state)."""
    n_out: int = 0
    alpha: float = 0.05         # center update rate
    lambda_: float = 0.5        # center-loss weight
    weight_init: str = "XAVIER"
    consumes_labels = True      # graph builds need a labels placeholder

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        from deeplearning4j_tpu.nn.layers import _attach_loss_head
        lname = ctx.lname("centerout")
        n_in = itype.flat_size
        w = ctx.param(f"{lname}_W", (n_in, self.n_out), self.weight_init)
        b = ctx.sd.var(f"{lname}_b", value=np.zeros(self.n_out),
                       dtype=ctx.dtype)
        z = x.mmul(w).add(b, name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, "softmax", lname)
        _attach_loss_head(ctx, z, out, "MCXENT")
        if ctx.training and ctx.labels_var is not None:
            centers = ctx.state(f"{lname}_centers",
                                np.zeros((self.n_out, n_in)))
            # class centers for this batch: labels (B,C) one-hot @ centers
            my_center = ctx.sd.invoke("matmul", [ctx.labels_var, centers],
                                      {}, name=f"{lname}_mycenter")
            diff = x.sub(my_center, name=f"{lname}_diff")
            closs = diff.square().mean().mul(0.5 * self.lambda_,
                                             name=f"{lname}_closs")
            closs.mark_as_loss()
            # EMA center update: c_k += alpha * mean_batch(x - c_k) per class
            upd = ctx.sd.invoke(
                "matmul", [ctx.labels_var, diff],
                {"transpose_a": True}, name=f"{lname}_updsum")
            cnt = ctx.sd.invoke("reduce_sum", [ctx.labels_var],
                                {"axis": (0,), "keep_dims": True},
                                name=f"{lname}_cnt")
            new_centers = centers.add(
                upd.div(cnt.transpose().add(1e-8)).mul(self.alpha),
                name=f"{lname}_newc")
            ctx.sd.update_state(centers, new_centers)
        return out, self.output_type(itype)


@dataclasses.dataclass
class FrozenLayer(BaseLayer):
    """Wraps a layer and freezes its parameters (reference:
    misc/FrozenLayer.java — gradients neither computed nor applied)."""
    layer: Optional[BaseLayer] = None

    def output_type(self, itype):
        return self.layer.output_type(itype)

    def build(self, ctx, x, itype):
        before = set(ctx.sd.trainable_params())
        out, otype = self.layer.build(ctx, x, itype)
        for name in set(ctx.sd.trainable_params()) - before:
            ctx.sd.convert_to_constant(ctx.sd.get_variable(name))
        return out, otype

    def to_json(self):
        return {"@class": "FrozenLayer", "layer": self.layer.to_json()}

    @staticmethod
    def _from_json_fields(d):
        return FrozenLayer(layer=BaseLayer.from_json(d["layer"]))


for _cls in [VariationalAutoencoderLayer, Yolo2OutputLayer,
             PrimaryCapsulesLayer, CapsuleLayer, CapsuleStrengthLayer,
             DotProductAttentionLayer, RecurrentAttentionLayer,
             GravesLSTMLayer, GRULayer, RepeatVectorLayer, PReLULayer,
             ElementWiseMultiplicationLayer, Subsampling1DLayer,
             ZeroPadding1DLayer, Cropping1DLayer, Upsampling1DLayer,
             Upsampling3DLayer, ZeroPadding3DLayer, SpaceToDepthLayer,
             DepthToSpaceLayer, CnnLossLayer, RnnLossLayer,
             CenterLossOutputLayer, FrozenLayer]:
    LAYER_TYPES[_cls.__name__] = _cls


def _itype_from_channels_last_shape(shape):
    """Per-sample channels-last shape -> InputType (Keras Reshape/Permute
    semantics; runtime tensors are channels-last for cnn under NHWC)."""
    dims = tuple(int(d) for d in shape)
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:          # (T, C)
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:          # (H, W, C)
        return InputType("cnn", (dims[2], dims[0], dims[1]))
    raise ValueError(f"unsupported reshape target {shape}")


@dataclasses.dataclass
class ReshapeLayer(BaseLayer):
    """Per-sample reshape with channels-last semantics (Keras Reshape;
    reference analogue: ReshapeVertex). target_shape excludes batch."""
    target_shape: Tuple[int, ...] = ()

    def output_type(self, itype):
        return _itype_from_channels_last_shape(self.target_shape)

    def build(self, ctx, x, itype):
        if itype.kind in ("cnn", "cnn3d") and ctx.cnn_format != "NHWC":
            raise ValueError("ReshapeLayer defines channels-last semantics; "
                             "build the net with cnn_data_format='NHWC'")
        out = ctx.sd.invoke("reshape", [x],
                            {"shape": (-1,) + tuple(self.target_shape)},
                            name=ctx.lname("reshape"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class PermuteLayer(BaseLayer):
    """Permute non-batch axes, 1-based like Keras Permute, over the
    channels-last view of the tensor."""
    dims: Tuple[int, ...] = (2, 1)

    def output_type(self, itype):
        if itype.kind == "rnn":
            t, c = itype.dims[1], itype.dims[0]
            cur = (t, c)
        elif itype.kind == "cnn":
            c, h, w = itype.dims
            cur = (h, w, c)
        else:
            raise ValueError("PermuteLayer needs rnn or cnn input")
        new = tuple(cur[d - 1] for d in self.dims)
        return _itype_from_channels_last_shape(new)

    def build(self, ctx, x, itype):
        if itype.kind == "cnn" and ctx.cnn_format != "NHWC":
            raise ValueError("PermuteLayer defines channels-last semantics; "
                             "build the net with cnn_data_format='NHWC'")
        axes = (0,) + tuple(self.dims)
        out = ctx.sd.invoke("permute", [x], {"axes": axes},
                            name=ctx.lname("permute"))
        return out, self.output_type(itype)


for _cls in [ReshapeLayer, PermuteLayer]:
    LAYER_TYPES[_cls.__name__] = _cls


@dataclasses.dataclass
class Cropping3DLayer(BaseLayer):
    """(reference: convolutional/Cropping3D.java)
    cropping = (d0, d1, h0, h1, w0, w1)."""
    cropping: Tuple[int, int, int, int, int, int] = (0, 0, 0, 0, 0, 0)

    def output_type(self, itype):
        c, d, h, w = itype.dims
        cr = self.cropping
        return InputType("cnn3d", (c, d - cr[0] - cr[1],
                                   h - cr[2] - cr[3], w - cr[4] - cr[5]))

    def build(self, ctx, x, itype):
        c, d, h, w = itype.dims
        cr = self.cropping
        big = 2**31 - 1
        if ctx.cnn_format == "NHWC":         # runtime NDHWC
            begin = (0, cr[0], cr[2], cr[4], 0)
            end = (big, d - cr[1], h - cr[3], w - cr[5], big)
        else:
            begin = (0, 0, cr[0], cr[2], cr[4])
            end = (big, big, d - cr[1], h - cr[3], w - cr[5])
        out = ctx.sd.invoke(
            "strided_slice", [x],
            {"begin": begin, "end": end, "strides": (1,) * 5},
            name=ctx.lname("crop3d"))
        return out, self.output_type(itype)


LAYER_TYPES[Cropping3DLayer.__name__] = Cropping3DLayer
