"""Convolutional layer breadth: 1D/3D, transposed, separable, depthwise,
LRN, upsampling, padding/cropping.

Reference parity: nn/conf/layers/{Convolution1DLayer, Convolution3D,
Deconvolution2D, SeparableConvolution2D, DepthwiseConvolution2D,
LocalResponseNormalization, Upsampling2D, ZeroPaddingLayer,
Cropping2D}.java. TPU-native: each config's ``build`` records one fused
XLA conv (lax.conv_general_dilated under the named op) instead of the
reference's im2col+gemm helper chain; layouts NCHW/HWIO, NCW sequences
presented as (B, T, C).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.layers import (
    BaseLayer, InputType, LAYER_TYPES, _as_pair, _conv_out, _maybe_dropout,
    _pad_mode)


def _as_triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


@dataclasses.dataclass
class Convolution1DLayer(BaseLayer):
    """1D conv over sequences (B, T, C) (reference:
    nn/conf/layers/Convolution1DLayer; native conv1d,
    generic/nn/convo/conv1d.cpp)."""
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    convolution_mode: str = "SAME"
    dilation: int = 1
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True
    dropout: float = 0.0

    def output_type(self, itype):
        c, t = itype.dims
        t_out = _conv_out(t, self.kernel_size, self.stride,
                          self.convolution_mode, self.dilation) \
            if t > 0 else t
        return InputType.recurrent(self.n_out, t_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("conv1d")
        c_in = itype.dims[0]
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w = ctx.param(f"{lname}_W", (self.kernel_size, c_in, self.n_out),
                      self.weight_init)
        inputs = [x, w]
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            inputs.append(b)
        z = ctx.sd.invoke("conv1d", inputs,
                          {"stride": self.stride,
                           "padding": _pad_mode(self.convolution_mode),
                           "dilation": self.dilation,
                           "data_format": "NWC"},
                          name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class Convolution3DLayer(BaseLayer):
    """3D conv over volumes (B, C, D, H, W) (reference:
    nn/conf/layers/Convolution3D; native conv3dnew)."""
    n_out: int = 0
    kernel_size: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    convolution_mode: str = "SAME"
    dilation: Tuple[int, int, int] = (1, 1, 1)
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        c = itype.dims[0]
        ks, ss, ds = _as_triple(self.kernel_size), _as_triple(self.stride), \
            _as_triple(self.dilation)
        spatial = tuple(
            _conv_out(itype.dims[1 + i], ks[i], ss[i],
                      self.convolution_mode, ds[i]) for i in range(3))
        return InputType("cnn3d", (self.n_out,) + spatial)

    def build(self, ctx, x, itype):
        lname = ctx.lname("conv3d")
        c_in = itype.dims[0]
        kd, kh, kw = _as_triple(self.kernel_size)
        w = ctx.param(f"{lname}_W", (kd, kh, kw, c_in, self.n_out),
                      self.weight_init)
        inputs = [x, w]
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            inputs.append(b)
        fmt3d = "NDHWC" if ctx.cnn_format == "NHWC" else "NCDHW"
        z = ctx.sd.invoke("conv3d", inputs,
                          {"strides": _as_triple(self.stride),
                           "padding": _pad_mode(self.convolution_mode),
                           "dilation": _as_triple(self.dilation),
                           "data_format": fmt3d},
                          name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class Subsampling3DLayer(BaseLayer):
    """3D pooling (reference: nn/conf/layers/Subsampling3DLayer)."""
    pooling_type: str = "MAX"
    kernel_size: Tuple[int, int, int] = (2, 2, 2)
    stride: Optional[Tuple[int, int, int]] = None
    convolution_mode: str = "VALID"

    def output_type(self, itype):
        c = itype.dims[0]
        ks = _as_triple(self.kernel_size)
        ss = _as_triple(self.stride or self.kernel_size)
        spatial = tuple(
            _conv_out(itype.dims[1 + i], ks[i], ss[i],
                      self.convolution_mode) for i in range(3))
        return InputType("cnn3d", (c,) + spatial)

    def build(self, ctx, x, itype):
        lname = ctx.lname("pool3d")
        op = {"MAX": "max_pool3d", "AVG": "avg_pool3d"}[
            self.pooling_type.upper()]
        fmt3d = "NDHWC" if ctx.cnn_format == "NHWC" else "NCDHW"
        out = ctx.sd.invoke(op, [x],
                            {"kernel": _as_triple(self.kernel_size),
                             "strides": _as_triple(self.stride
                                                   or self.kernel_size),
                             "padding": _pad_mode(self.convolution_mode),
                             "data_format": fmt3d},
                            name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class Deconvolution2DLayer(BaseLayer):
    """Transposed conv (reference: nn/conf/layers/Deconvolution2D; native
    deconv2d, generic/nn/convo/deconv2d.cpp)."""
    n_out: int = 0
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    convolution_mode: str = "SAME"
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        if self.convolution_mode.upper() == "SAME":
            oh, ow = h * sh, w * sw
        else:
            # lax.conv_transpose VALID: (h-1)*s + max(k, s)
            oh = (h - 1) * sh + max(kh, sh)
            ow = (w - 1) * sw + max(kw, sw)
        return InputType("cnn", (self.n_out, oh, ow))

    def build(self, ctx, x, itype):
        lname = ctx.lname("deconv")
        c_in = itype.dims[0]
        kh, kw = _as_pair(self.kernel_size)
        # weights stored like the fwd conv they transpose: (kH,kW,oC,iC)
        w = ctx.param(f"{lname}_W", (kh, kw, self.n_out, c_in),
                      self.weight_init)
        inputs = [x, w]
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            inputs.append(b)
        z = ctx.sd.invoke("deconv2d", inputs,
                          {"strides": _as_pair(self.stride),
                           "padding": _pad_mode(self.convolution_mode),
                           "data_format": ctx.cnn_format},
                          name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class DepthwiseConvolution2DLayer(BaseLayer):
    """Depthwise conv (reference: nn/conf/layers/DepthwiseConvolution2D;
    native depthwise_conv2d)."""
    depth_multiplier: int = 1
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    convolution_mode: str = "SAME"
    dilation: Tuple[int, int] = (1, 1)
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        dh, dw = _as_pair(self.dilation)
        return InputType("cnn", (c * self.depth_multiplier,
                                 _conv_out(h, kh, sh, self.convolution_mode, dh),
                                 _conv_out(w, kw, sw, self.convolution_mode, dw)))

    def build(self, ctx, x, itype):
        lname = ctx.lname("dwconv")
        c_in = itype.dims[0]
        kh, kw = _as_pair(self.kernel_size)
        w = ctx.param(f"{lname}_W", (kh, kw, c_in, self.depth_multiplier),
                      self.weight_init)
        inputs = [x, w]
        if self.has_bias:
            b = ctx.sd.var(
                f"{lname}_b",
                value=np.full((c_in * self.depth_multiplier,),
                              self.bias_init),
                dtype=ctx.dtype)
            inputs.append(b)
        z = ctx.sd.invoke("depthwise_conv2d", inputs,
                          {"strides": _as_pair(self.stride),
                           "padding": _pad_mode(self.convolution_mode),
                           "dilation": _as_pair(self.dilation),
                           "data_format": ctx.cnn_format},
                          name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class SeparableConvolution2DLayer(BaseLayer):
    """Depthwise-separable conv (reference:
    nn/conf/layers/SeparableConvolution2D; native sconv2d)."""
    n_out: int = 0
    depth_multiplier: int = 1
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    convolution_mode: str = "SAME"
    dilation: Tuple[int, int] = (1, 1)
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        dh, dw = _as_pair(self.dilation)
        return InputType("cnn", (self.n_out,
                                 _conv_out(h, kh, sh, self.convolution_mode, dh),
                                 _conv_out(w, kw, sw, self.convolution_mode, dw)))

    def build(self, ctx, x, itype):
        lname = ctx.lname("sepconv")
        c_in = itype.dims[0]
        kh, kw = _as_pair(self.kernel_size)
        dw = ctx.param(f"{lname}_dW", (kh, kw, c_in, self.depth_multiplier),
                       self.weight_init)
        pw = ctx.param(f"{lname}_pW",
                       (1, 1, c_in * self.depth_multiplier, self.n_out),
                       self.weight_init)
        inputs = [x, dw, pw]
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            inputs.append(b)
        z = ctx.sd.invoke("separable_conv2d", inputs,
                          {"strides": _as_pair(self.stride),
                           "padding": _pad_mode(self.convolution_mode),
                           "dilation": _as_pair(self.dilation),
                           "data_format": ctx.cnn_format},
                          name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class LocalResponseNormalization(BaseLayer):
    """LRN across channels (reference:
    nn/conf/layers/LocalResponseNormalization — k/n/alpha/beta; native
    generic/nn/lrn.cpp)."""
    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("lrn")
        if int(self.n) % 2 == 0:
            raise ValueError(
                f"LRN window n={self.n} must be odd (symmetric window "
                f"2*(n//2)+1); even n would silently widen the window")
        # op takes depth = half window n/2, reference convention
        out = ctx.sd.invoke("lrn", [x],
                            {"depth": int(self.n) // 2, "bias": self.k,
                             "alpha": self.alpha, "beta": self.beta,
                             "data_format": ctx.cnn_format},
                            name=lname)
        return out, itype


@dataclasses.dataclass
class Upsampling2DLayer(BaseLayer):
    """Nearest-neighbour upsampling (reference:
    nn/conf/layers/Upsampling2D)."""
    size: Tuple[int, int] = (2, 2)

    def output_type(self, itype):
        c, h, w = itype.dims
        fh, fw = _as_pair(self.size)
        return InputType("cnn", (c, h * fh, w * fw))

    def build(self, ctx, x, itype):
        out = ctx.sd.invoke("upsampling2d", [x],
                            {"factor": _as_pair(self.size),
                             "data_format": ctx.cnn_format},
                            name=ctx.lname("upsample"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class ZeroPaddingLayer(BaseLayer):
    """Spatial zero padding (reference: nn/conf/layers/ZeroPaddingLayer).
    padding = (top, bottom, left, right)."""
    padding: Tuple[int, int, int, int] = (1, 1, 1, 1)

    def output_type(self, itype):
        c, h, w = itype.dims
        t, b, l, r = self.padding
        return InputType("cnn", (c, h + t + b, w + l + r))

    def build(self, ctx, x, itype):
        t, b, l, r = self.padding
        if ctx.cnn_format == "NHWC":
            pads = ((0, 0), (t, b), (l, r), (0, 0))
        else:
            pads = ((0, 0), (0, 0), (t, b), (l, r))
        out = ctx.sd.invoke(
            "pad", [x], {"paddings": pads}, name=ctx.lname("zeropad"))
        return out, self.output_type(itype)


@dataclasses.dataclass
class Cropping2DLayer(BaseLayer):
    """Spatial cropping (reference: nn/conf/layers/convolutional/
    Cropping2D). cropping = (top, bottom, left, right)."""
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def output_type(self, itype):
        c, h, w = itype.dims
        t, b, l, r = self.cropping
        return InputType("cnn", (c, h - t - b, w - l - r))

    def build(self, ctx, x, itype):
        c, h, w = itype.dims
        t, b, l, r = self.cropping
        big = 2**31 - 1
        if ctx.cnn_format == "NHWC":
            begin, end = (0, t, l, 0), (big, h - b, w - r, big)
        else:
            begin, end = (0, 0, t, l), (big, big, h - b, w - r)
        out = ctx.sd.invoke(
            "strided_slice", [x],
            {"begin": begin, "end": end, "strides": (1, 1, 1, 1)},
            name=ctx.lname("crop"))
        return out, self.output_type(itype)


for _cls in [Convolution1DLayer, Convolution3DLayer, Subsampling3DLayer,
             Deconvolution2DLayer, DepthwiseConvolution2DLayer,
             SeparableConvolution2DLayer, LocalResponseNormalization,
             Upsampling2DLayer, ZeroPaddingLayer, Cropping2DLayer]:
    LAYER_TYPES[_cls.__name__] = _cls
