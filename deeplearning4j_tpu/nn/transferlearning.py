"""Transfer learning: freeze / replace / fine-tune over built networks.

Reference parity: nn/transferlearning/TransferLearning.java:1 —
Builder(origModel).fineTuneConfiguration(...).setFeatureExtractor(idx)
.nOutReplace(idx, nOut).removeOutputLayer().addLayer(...).build(), plus
FineTuneConfiguration. The graph primitive underneath is the same as the
reference's FrozenLayer wrapping: frozen layers' parameters become
CONSTANTS in the compiled train step (convert_to_constant — they are
baked into the XLA computation and get no gradients), and retained
weights copy by parameter name.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import BaseLayer
from deeplearning4j_tpu.nn.layers_ext import FrozenLayer


class FineTuneConfiguration:
    """(reference: transferlearning/FineTuneConfiguration.java) — global
    overrides applied to the transferred model's training config."""

    def __init__(self, updater=None, seed: Optional[int] = None):
        self.updater = updater
        self.seed = seed

    def __repr__(self):
        return (f"FineTuneConfiguration(updater={self.updater!r}, "
                f"seed={self.seed!r})")


class TransferLearning:
    """Builder over a trained MultiLayerNetwork."""

    class Builder:
        def __init__(self, net):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            if not isinstance(net, MultiLayerNetwork):
                raise TypeError("TransferLearning.Builder takes a "
                                "MultiLayerNetwork")
            net._require_init()
            self._net = net
            self._layers: List[BaseLayer] = [copy.deepcopy(l)
                                             for l in net.conf.layers]
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._kept = len(self._layers)   # layers whose weights copy over

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference:
            setFeatureExtractor — 'up to and including')."""
            self._freeze_until = int(layer_idx)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from(len(self._layers) - 1)

        def remove_layers_from(self, layer_idx: int):
            """Drop layers [layer_idx..end]."""
            self._layers = self._layers[:layer_idx]
            self._kept = min(self._kept, layer_idx)
            return self

        def add_layer(self, layer: BaseLayer):
            self._layers.append(layer)
            return self

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: Optional[str] = None):
            """Replace layer_idx's output width; its weights (and every
            later layer's) re-initialize (reference: nOutReplace)."""
            l = copy.deepcopy(self._layers[layer_idx])
            if not hasattr(l, "n_out"):
                raise ValueError(f"layer {layer_idx} "
                                 f"({type(l).__name__}) has no n_out")
            l.n_out = int(n_out)
            if weight_init is not None and hasattr(l, "weight_init"):
                l.weight_init = weight_init
            self._layers[layer_idx] = l
            self._kept = min(self._kept, layer_idx)
            return self

        def build(self):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            old = self._net.conf
            layers = list(self._layers)
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    if not isinstance(layers[i], FrozenLayer):
                        layers[i] = FrozenLayer(layer=layers[i])
            ftc = self._fine_tune
            conf = MultiLayerConfiguration(
                layers=layers,
                input_type=old.input_type,
                seed=(ftc.seed if ftc and ftc.seed is not None else old.seed),
                updater=(ftc.updater if ftc and ftc.updater is not None
                         else old.updater),
                regularization=old.regularization,
                dtype=old.dtype,
                grad_clip_value=old.grad_clip_value,
                mixed_precision=old.mixed_precision,
                gradient_normalization=old.gradient_normalization,
                gradient_normalization_threshold=
                    old.gradient_normalization_threshold,
                cnn_data_format=old.cnn_data_format,
            )
            new_net = MultiLayerNetwork(conf).init()
            self._copy_weights(new_net)
            return new_net

        def _copy_weights(self, new_net):
            """Copy parameter arrays for retained layers by name; layer
            indices are positional, so params keep their 'layer{i}_*'
            names for every kept prefix layer."""
            import jax.numpy as jnp
            src = self._net._sd_train
            kept_prefixes = tuple(f"layer{i}_" for i in range(self._kept))
            for tgt in (new_net._sd_train, new_net._sd_infer):
                for name, arr in src._arrays.items():
                    if not name.startswith(kept_prefixes):
                        continue
                    if name in tgt._arrays and \
                            tuple(tgt._arrays[name].shape) == tuple(arr.shape):
                        tgt._arrays[name] = jnp.asarray(arr)

    @staticmethod
    def builder(net) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)
