"""MultiLayerNetwork — sequential network compiled through SameDiff.

Reference parity: org.deeplearning4j.nn.multilayer.MultiLayerNetwork
(MultiLayerNetwork.java — fit :1647/1664, output :2471, score, save/load via
util/ModelSerializer). The reference runs per-layer imperative
forward/backprop with per-op JNI dispatch inside Solver/StochasticGradient-
Descent (SURVEY.md §3.2); here `fit` delegates to the SameDiff whole-graph
training step — one compiled XLA computation per minibatch shape, params
donated between steps.

Two graphs are built from the same config + seed (identical parameter names
and initial values): a training graph (dropout active, batch-stat BN with
running-stat state updates) and an inference graph (no dropout, running-stat
BN). Parameters live in the training graph; `output()` syncs them (reference
analogue: the single parameter view array shared by train/eval paths).
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import (
    BaseLayer, BuildContext, ConvolutionLayer, DenseLayer, EmbeddingLayer,
    GlobalPoolingLayer, InputType, LSTMLayer, OutputLayer, SubsamplingLayer)

_WANTED_KIND = {
    # accepted input kinds per layer class; first entry = preferred kind a
    # preprocessor should convert to when none of the accepted kinds match
    "DenseLayer": ("ff", "rnn"),   # rnn input = per-timestep dense
    "OutputLayer": ("ff",), "EmbeddingLayer": ("ff",),
    "ConvolutionLayer": ("cnn",), "SubsamplingLayer": ("cnn",),
    "LSTMLayer": ("rnn",), "SimpleRnnLayer": ("rnn",),
    "Bidirectional": ("rnn",), "RnnOutputLayer": ("rnn",),
    "LastTimeStepLayer": ("rnn",), "Convolution1DLayer": ("rnn",),
    "Convolution3DLayer": ("cnn3d",), "Subsampling3DLayer": ("cnn3d",),
    "Deconvolution2DLayer": ("cnn",), "DepthwiseConvolution2DLayer": ("cnn",),
    "SeparableConvolution2DLayer": ("cnn",),
    "LocalResponseNormalization": ("cnn",), "Upsampling2DLayer": ("cnn",),
    "ZeroPaddingLayer": ("cnn",), "Cropping2DLayer": ("cnn",),
    # wave 2 (layers_ext)
    "VariationalAutoencoderLayer": ("ff",),
    "Yolo2OutputLayer": ("cnn",), "PrimaryCapsulesLayer": ("cnn",),
    "DotProductAttentionLayer": ("rnn",),
    "RecurrentAttentionLayer": ("rnn",),
    "GravesLSTMLayer": ("rnn",), "GRULayer": ("rnn",),
    "RepeatVectorLayer": ("ff",),
    "ElementWiseMultiplicationLayer": ("ff",),
    "Subsampling1DLayer": ("rnn",), "ZeroPadding1DLayer": ("rnn",),
    "Cropping1DLayer": ("rnn",), "Upsampling1DLayer": ("rnn",),
    "Upsampling3DLayer": ("cnn3d",), "ZeroPadding3DLayer": ("cnn3d",),
    "SpaceToDepthLayer": ("cnn",), "DepthToSpaceLayer": ("cnn",),
    "CnnLossLayer": ("cnn",), "RnnLossLayer": ("rnn",),
    "CenterLossOutputLayer": ("ff",),
}


def _adapt_itype(itype: InputType, layer: BaseLayer, idx: int) -> InputType:
    """Preprocessor-kind rule — the ONE place deciding how an input type
    adapts to a layer's wanted kind (reference:
    nn/conf/preprocessor/{CnnToFeedForward,...}PreProcessor, added
    automatically by setInputType). Used by both graph build and type
    walking so they cannot desynchronize."""
    # wrapper layers adapt by their INNER layer's wanted kind
    probe = layer
    while type(probe).__name__ == "FrozenLayer" and \
            getattr(probe, "layer", None) is not None:
        probe = probe.layer
    accepted = _WANTED_KIND.get(type(probe).__name__)
    if accepted is None or itype.kind in accepted:
        return itype
    wanted = accepted[0]
    if itype.kind in ("cnn", "cnn3d") and wanted == "ff":
        return InputType.feed_forward(itype.flat_size)
    if itype.kind == "rnn" and wanted == "ff":
        # reference RnnToFeedForwardPreProcessor merges time into batch;
        # here the common intent after an LSTM is "last step" — use
        # LSTMLayer(return_sequences=False) or GlobalPoolingLayer instead
        raise ValueError(
            f"layer {idx} ({type(layer).__name__}) wants flat input but got "
            f"a sequence; use LSTMLayer(return_sequences=False) or "
            f"GlobalPoolingLayer before it")
    raise ValueError(f"no preprocessor from {itype.kind} to {wanted} "
                     f"(layer {idx}, {type(layer).__name__})")


def _adapt_input(sd, x, itype: InputType, layer: BaseLayer, idx,
                 name_stem: Optional[str] = None):
    """Apply _adapt_itype's decision to the graph (emit the reshape).
    Shared by MultiLayerNetwork and ComputationGraph builds."""
    new_itype = _adapt_itype(itype, layer, idx)
    if new_itype is itype:
        return x, itype
    x = sd.invoke("reshape", [x], {"shape": (-1, new_itype.flat_size)},
                  name=name_stem or f"layer{idx}_cnn2ff")
    return x, new_itype


def _type_walk(conf: MultiLayerConfiguration):
    """Yield (idx, layer, adapted input type, output type) — the single
    source of truth for preprocessor-kind adaptation, shared by graph
    build sizing, summary() and _final_output_type()."""
    itype = conf.input_type
    for idx, layer in enumerate(conf.layers):
        itype = _adapt_itype(itype, layer, idx)
        otype = layer.output_type(itype)
        yield idx, layer, itype, otype
        itype = otype


def _final_output_type(conf: MultiLayerConfiguration) -> InputType:
    itype = conf.input_type
    for _, _, _, otype in _type_walk(conf):
        itype = otype
    return itype


def _to_internal_layout(sd, x, itype: InputType, fmt: str, name: str):
    """Users feed NCHW (reference convention); internally cnn tensors run
    NHWC on TPU (one permute here, none in the network body — logical-NCHW
    convs cost a physical transpose per op on TPU, see PROFILE.md)."""
    if fmt != "NHWC" or itype.kind not in ("cnn", "cnn3d"):
        return x
    axes = (0, 2, 3, 1) if itype.kind == "cnn" else (0, 2, 3, 4, 1)
    return sd.invoke("permute", [x], {"axes": axes}, name=name)


def _to_external_layout(sd, x, itype: InputType, fmt: str, name: str):
    """Inverse of _to_internal_layout for cnn-typed network outputs."""
    if fmt != "NHWC" or itype.kind not in ("cnn", "cnn3d"):
        return x
    axes = (0, 3, 1, 2) if itype.kind == "cnn" else (0, 4, 1, 2, 3)
    return sd.invoke("permute", [x], {"axes": axes}, name=name)


def _build_graph(conf: MultiLayerConfiguration, training: bool,
                 tbptt_batch=None):
    sd = SameDiff()
    rng = np.random.default_rng(conf.seed)
    fmt = getattr(conf, "cnn_data_format", "NHWC")
    ctx = BuildContext(sd=sd, rng=rng, training=training, dtype=conf.dtype,
                       cnn_format=fmt, tbptt_batch=tbptt_batch)
    x = sd.placeholder("input", shape=conf.input_type.placeholder_shape(),
                       dtype=conf.dtype)
    final = _final_output_type(conf)
    # labels default to the head's output shape; heads whose target
    # layout differs (yolo: (B, 4+C, H, W) vs the A*(5+C) prediction
    # grid) override via labels_placeholder_shape — a wrong declared
    # shape is never enforced at feed time, but it poisons shape
    # inference and the static analyzer (graph.shape_mismatch)
    lab_hook = getattr(conf.layers[-1] if conf.layers else None,
                       "labels_placeholder_shape", None)
    lab_shape = lab_hook(final) if lab_hook is not None else None
    ctx.labels_var = sd.placeholder(
        "labels",
        shape=lab_shape if lab_shape is not None
        else final.placeholder_shape(),
        dtype=conf.dtype)
    cur = _to_internal_layout(sd, x, conf.input_type, fmt, "input_nhwc")
    itype = conf.input_type
    for idx, layer in enumerate(conf.layers):
        cur, itype = _adapt_input(sd, cur, itype, layer, idx)
        ctx.idx = idx
        cur, itype = layer.build(ctx, cur, itype)
    if ctx.output_var is None:
        ctx.output_var = cur
    if itype.kind in ("cnn", "cnn3d"):
        # cnn-typed network output goes back to the external NCHW contract
        # (also when a loss head set output_var itself, e.g. Yolo2/CnnLoss)
        ctx.output_var = _to_external_layout(sd, ctx.output_var, itype, fmt,
                                             "output_nchw")
    ctx.output_var.rename("output")
    return sd, ctx


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self._sd_train: Optional[SameDiff] = None
        self._sd_infer: Optional[SameDiff] = None
        self._score = float("nan")

    # ------------------------------------------------------------------
    def init(self) -> "MultiLayerNetwork":
        """Build both graphs (reference: MultiLayerNetwork.init())."""
        self._sd_train, _ = _build_graph(self.conf, training=True)
        self._sd_infer, _ = _build_graph(self.conf, training=False)
        self._sd_train.training_config = TrainingConfig(
            updater=self.conf.updater,
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["labels"],
            regularization=self.conf.regularization,
            grad_clip_value=self.conf.grad_clip_value,
            mixed_precision=self.conf.mixed_precision,
            gradient_normalization=self.conf.gradient_normalization,
            gradient_normalization_threshold=
                self.conf.gradient_normalization_threshold,
        )
        return self

    def _require_init(self):
        if self._sd_train is None:
            raise RuntimeError("call init() first")

    @property
    def samediff(self) -> SameDiff:
        """The underlying training graph (single execution path)."""
        self._require_init()
        return self._sd_train

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32,
            listeners: Sequence = (), fused_steps: Optional[int] = None,
            accum_steps: Optional[int] = None,
            sentinel: Optional[bool] = None):
        """Train. ``data`` = DataSetIterator-alike (yielding (features,
        labels) / DataSet / dict) or a feature array with ``labels=``.

        ``fused_steps``/``accum_steps`` override the TrainingConfig knobs
        for this and subsequent fits: K fused steps per compiled dispatch
        / gradient accumulation (docs/training_performance.md).
        ``sentinel`` arms the device-side divergence sentinel
        (docs/fault_tolerance.md)."""
        self._require_init()
        if fused_steps is not None:
            self._sd_train.training_config.fused_steps = int(fused_steps)
        if accum_steps is not None:
            self._sd_train.training_config.accum_steps = int(accum_steps)
        if sentinel is not None:
            self._sd_train.training_config.sentinel = bool(sentinel)
        if labels is not None:
            data = _ArrayIterator(np.asarray(data), np.asarray(labels),
                                  batch_size)
        history = self._sd_train.fit(data, epochs=epochs, listeners=listeners)
        self._score = history.final_loss()
        return history

    def fit_tbptt(self, features, labels, tbptt_length: int,
                  epochs: int = 1, batch_size: int = 32):
        """Truncated backprop through time (reference:
        MultiLayerNetwork.doTruncatedBPTT, MultiLayerNetwork.java:2083).

        features (B, T, C) / labels (B, T, C_out) split into
        ``tbptt_length`` chunks along time. TPU-native design: each
        recurrent layer's initial state is a persistent STATE VAR carried
        across chunk steps by the compiled train step (state-var inputs
        are stop-gradiented there, which IS the truncation); states reset
        to zero per sequence minibatch. Equivalent to full BPTT when
        tbptt_length >= T (tested).

        Truncation segments are a natural fused window: all full-length
        chunks of one minibatch dispatch as ONE compiled lax.scan
        (SameDiff.make_train_window), with a single extra dispatch for a
        ragged final chunk when ``T % tbptt_length != 0``. Per-chunk
        losses stay in the window's device-side buffer — ONE stacked
        fetch per fit instead of thousands of device scalars held across
        epochs."""
        import jax
        import jax.numpy as jnp
        self._require_init()
        X = np.asarray(features)
        Y = np.asarray(labels)
        if X.ndim != 3 or Y.ndim != 3:
            raise ValueError("fit_tbptt needs sequence features (B, T, C) "
                             "and per-timestep labels (B, T, C_out)")
        T = X.shape[1]
        if Y.shape[1] != T:
            raise ValueError(f"labels T={Y.shape[1]} != features T={T}")
        # dedicated TBPTT train graph for this batch size (cached)
        key = ("tbptt", batch_size)
        cached = getattr(self, "_tbptt_graphs", None) or {}
        if key not in cached:
            sd, ctx = _build_graph(self.conf, training=True,
                                   tbptt_batch=batch_size)
            sd.training_config = TrainingConfig(
                updater=self.conf.updater,
                data_set_feature_mapping=["input"],
                data_set_label_mapping=["labels"],
                regularization=self.conf.regularization,
                grad_clip_value=self.conf.grad_clip_value,
                mixed_precision=self.conf.mixed_precision,
                gradient_normalization=self.conf.gradient_normalization,
                gradient_normalization_threshold=
                    self.conf.gradient_normalization_threshold)
            cached[key] = (sd, list(ctx.rnn_state_vars))
            self._tbptt_graphs = cached
        sd, rnn_states = cached[key]
        # current weights in (same names, same init seed)
        for n, arr in self._sd_train._arrays.items():
            if n in sd._arrays and \
                    tuple(sd._arrays[n].shape) == tuple(arr.shape):
                sd._arrays[n] = arr

        from deeplearning4j_tpu.autodiff.training import History
        # the divergence sentinel follows the network's main config onto
        # the dedicated TBPTT graph — an armed rail must not silently go
        # inert on this fit path (docs/fault_tolerance.md)
        use_sentinel = bool(getattr(self._sd_train.training_config,
                                    "sentinel", False))
        sd.training_config.sentinel = use_sentinel
        step = sd.make_train_step(sentinel=use_sentinel)
        window_fn = sd.make_train_window(sentinel=use_sentinel)
        tc = sd.training_config
        params = jax.tree_util.tree_map(jnp.copy, sd.trainable_params())
        svars = jax.tree_util.tree_map(jnp.copy, sd.state_vars_map())
        # persist optimizer state across calls, like fit()
        if sd._updater_state is not None and \
                set(sd._updater_state.keys()) == set(params.keys()):
            state = jax.tree_util.tree_map(jnp.copy, sd._updater_state)
        else:
            state = tc.updater.init(params)
        constants = sd.constants_map()
        iteration = getattr(tc, "iteration_count", 0)
        it_dev = jnp.asarray(iteration, jnp.int32)
        base_key = jax.random.key(sd._seed)
        sd._seed += 1
        n = (len(X) // batch_size) * batch_size
        if n == 0:
            raise ValueError("dataset smaller than one batch")
        if n < len(X):
            import warnings
            warnings.warn(
                f"fit_tbptt: dropping {len(X) - n} of {len(X)} sequences "
                f"that do not fill a full batch of {batch_size} (TBPTT "
                f"state vars have a fixed batch dimension)")
        history = History()
        # host-side zero templates: fresh device arrays per batch (the
        # step DONATES state buffers, so device zeros can't be reused)
        zero_np = {nm: np.zeros(svars[nm].shape,
                                np.asarray(svars[nm]).dtype)
                   for nm in rnn_states}
        # truncation segments as ONE fused window per minibatch: the
        # n_full full-length chunks stack on a leading axis and dispatch
        # as one lax.scan; a ragged tail chunk (T % L != 0) is one extra
        # per-step dispatch of its own compiled shape (as before)
        n_full = T // tbptt_length
        rem = T % tbptt_length
        t_full = n_full * tbptt_length
        epoch_means = []   # DEVICE scalars; ONE stacked fetch at fit end
        for epoch in range(epochs):
            losses = []    # device loss buffers, never fetched per chunk
            bads = []      # sentinel markers, device (one per dispatch)
            epoch_start_iter = iteration
            for i in range(0, n, batch_size):
                # new sequences: recurrent carries restart at zero
                svars = {**svars, **{nm: jnp.asarray(z)
                                     for nm, z in zero_np.items()}}
                if n_full:
                    xb = X[i:i + batch_size, :t_full].reshape(
                        batch_size, n_full, tbptt_length, *X.shape[2:])
                    yb = Y[i:i + batch_size, :t_full].reshape(
                        batch_size, n_full, tbptt_length, *Y.shape[2:])
                    win = {"input": jnp.asarray(xb.swapaxes(0, 1)),
                           "labels": jnp.asarray(yb.swapaxes(0, 1))}
                    if use_sentinel:
                        (params, svars, state, it_dev, win_losses,
                         bad) = window_fn(params, svars, state, it_dev,
                                          constants, win, base_key)
                        bads.append(bad)
                    else:
                        params, svars, state, it_dev, win_losses = window_fn(
                            params, svars, state, it_dev, constants, win,
                            base_key)
                    iteration += n_full
                    losses.append(win_losses)
                if rem:
                    ph = {"input": jnp.asarray(X[i:i + batch_size, t_full:]),
                          "labels": jnp.asarray(Y[i:i + batch_size, t_full:])}
                    if use_sentinel:
                        params, svars, state, it_dev, loss_val, ok = step(
                            params, svars, state, it_dev, constants, ph,
                            base_key)
                        # normalize the per-step flag to the window
                        # tier's bad-step form (-1 = clean)
                        bads.append(jnp.where(ok, jnp.int32(-1),
                                              jnp.int32(iteration)))
                    else:
                        params, svars, state, it_dev, loss_val = step(
                            params, svars, state, it_dev, constants, ph,
                            base_key)
                    iteration += 1
                    losses.append(loss_val[None])
            if bads:
                # one stacked verdict fetch per epoch (the sentinel's
                # only extra sync on this path)
                from deeplearning4j_tpu.faults.sentinels import \
                    check_bad_steps
                check_bad_steps(np.asarray(jnp.stack(bads)), epoch,
                                epoch_start_iter)
            epoch_means.append(jnp.mean(jnp.concatenate(losses))
                               if losses else jnp.asarray(float("nan")))
            history.add_epoch(epoch, None)
        fetched = np.asarray(jnp.stack(epoch_means))     # one transfer
        history.loss_curve.losses = [float(v) for v in fetched]
        # trained params back into BOTH graphs (by name)
        for tgt in (sd, self._sd_train):
            for pn, arr in params.items():
                if pn in tgt._arrays:
                    tgt._arrays[pn] = arr
        for sn, arr in svars.items():
            if sn in sd._arrays:
                sd._arrays[sn] = arr
            if sn in self._sd_train._arrays and sn not in rnn_states:
                self._sd_train._arrays[sn] = arr   # e.g. BN running stats
        sd._updater_state = state
        tc.iteration_count = iteration
        self._score = history.final_loss()
        return history

    def _sync_infer(self):
        # same param names in both graphs; move references, not data
        tgt = self._sd_infer
        for n, arr in self._sd_train._arrays.items():
            if n in tgt._vars and n in tgt._arrays:
                tgt._arrays[n] = arr

    def serving_spec(self):
        """Replica-extraction hook for the serving/ subsystem: the
        inference graph, its IO names, and the parameter sync that pulls
        current trained weights into it. Serving executes the SAME graph
        ``output()`` uses, so served results match it bit for bit."""
        self._require_init()
        return self._sd_infer, ["input"], ["output"], self._sync_infer

    def output(self, x, training: bool = False):
        """Forward pass (reference: MultiLayerNetwork.output :2471)."""
        self._require_init()
        if training:
            return self._sd_train.output({"input": x}, ["output"])["output"]
        self._sync_infer()
        return self._sd_infer.output({"input": x}, ["output"])["output"]

    def predict(self, x) -> np.ndarray:
        """Class indices (reference: MultiLayerNetwork.predict)."""
        return np.asarray(self.output(x).to_numpy().argmax(axis=-1))

    def score(self) -> float:
        """Most recent training loss (reference: MultiLayerNetwork.score)."""
        return self._score

    def evaluate(self, data, labels=None, evaluation=None, batch_size: int = 256):
        """Evaluate over an iterator or arrays (reference:
        MultiLayerNetwork.evaluate(DataSetIterator)). Returns the
        Evaluation (or supplied metric accumulator) after streaming all
        batches through inference."""
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = evaluation or Evaluation()
        if labels is not None:
            data = _ArrayIterator(np.asarray(data), np.asarray(labels),
                                  batch_size)
        if hasattr(data, "reset"):
            data.reset()
        for batch in data:
            if isinstance(batch, dict):
                feats, labs = batch["input"], batch["labels"]
            elif hasattr(batch, "features"):
                feats, labs = batch.features, batch.labels
            else:
                feats, labs = batch
            preds = self.output(feats)
            ev.eval(labs, preds)
        return ev

    # ------------------------------------------------------------------
    def params(self) -> Dict[str, np.ndarray]:
        self._require_init()
        return {n: np.asarray(a) for n, a in
                {**self._sd_train.trainable_params(),
                 **self._sd_train.state_vars_map()}.items()}

    def set_param(self, name: str, value) -> None:
        self._require_init()
        self._sd_train.set_arr_for_var(name, value)

    def num_params(self) -> int:
        return sum(int(np.prod(a.shape))
                   for a in self._sd_train.trainable_params().values())

    def summary(self) -> str:
        lines = [f"MultiLayerNetwork: {len(self.conf.layers)} layers, "
                 f"{self.num_params() if self._sd_train else '?'} params"]
        for i, layer, itype, otype in _type_walk(self.conf):
            lines.append(f"  {i}: {type(layer).__name__:<22} "
                         f"{itype.dims} -> {otype.dims}")
        return "\n".join(lines)

    # ------------------------------------------------------------------
    # checkpointing (checkpoint/ subsystem: atomic, async, bit-exact)
    def capture_training_state(self, epoch: int = 0, normalizer=None):
        """Host snapshot of params/updater/counters/RNG for the
        checkpoint manager (checkpoint.capture_training_state)."""
        from deeplearning4j_tpu.checkpoint import capture_training_state
        self._require_init()
        return capture_training_state(self, epoch=epoch,
                                      normalizer=normalizer)

    def restore_training_state(self, state, strict: bool = True):
        """Restore a TrainingState snapshot into this initialized net;
        returns the rebuilt Normalizer (or None)."""
        from deeplearning4j_tpu.checkpoint import restore_training_state
        self._require_init()
        return restore_training_state(self, state, strict=strict)

    # ------------------------------------------------------------------
    # serde (reference: util/ModelSerializer zip of config JSON + params +
    # updater state)
    def save(self, path, include_updater_state: bool = True) -> None:
        from deeplearning4j_tpu.nn.model_serde import save_net_zip
        self._require_init()
        save_net_zip(path, self.conf.to_json(), self._sd_train,
                     include_updater_state)

    @staticmethod
    def load(path) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.nn.model_serde import (read_net_zip,
                                                       restore_net_state)
        conf_json, arrays, updater_leaves, iteration = read_net_zip(path)
        conf = MultiLayerConfiguration.from_json(conf_json)
        net = MultiLayerNetwork(conf).init()
        return restore_net_state(net, conf, arrays, updater_leaves, iteration)


class _ArrayIterator:
    """In-memory batch iterator over one or more feature/label arrays
    (shared by MultiLayerNetwork and ComputationGraph fit(X, Y) paths)."""

    def __init__(self, X, Y, batch: int):
        self.Xs = list(X) if isinstance(X, (list, tuple)) else [X]
        self.Ys = list(Y) if isinstance(Y, (list, tuple)) else [Y]
        self.batch = batch

    def reset(self):
        pass

    def __iter__(self):
        n = len(self.Xs[0])
        for i in range(0, n, self.batch):
            feats = [X[i:i + self.batch] for X in self.Xs]
            labs = [Y[i:i + self.batch] for Y in self.Ys]
            yield (feats if len(feats) > 1 else feats[0],
                   labs if len(labs) > 1 else labs[0])
