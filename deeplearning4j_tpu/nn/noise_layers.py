"""Noise/regularization layers: Gaussian noise & dropout variants.

Reference parity: the reference models these as IDropout implementations
applied inside layers (nn/conf/dropout/{GaussianNoise, GaussianDropout,
AlphaDropout, SpatialDropout}.java) and Keras imports them as standalone
layers; here they are standalone layers on the same random ops, active
only in the training graph (inference build is the identity).
"""
from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.layers import BaseLayer, LAYER_TYPES


def _passthrough_type(self, itype):
    return itype


@dataclasses.dataclass
class GaussianNoiseLayer(BaseLayer):
    """Additive N(0, stddev) noise at train time (reference:
    nn/conf/dropout/GaussianNoise.java)."""
    stddev: float = 0.1

    output_type = _passthrough_type

    def build(self, ctx, x, itype):
        if not ctx.training or self.stddev <= 0:
            return x, itype
        out = ctx.sd.invoke("gaussian_noise", [x],
                            {"stddev": self.stddev},
                            name=ctx.lname("gnoise"))
        return out, itype


@dataclasses.dataclass
class GaussianDropoutLayer(BaseLayer):
    """Multiplicative N(1, rate/(1-rate)) noise (reference:
    nn/conf/dropout/GaussianDropout.java)."""
    rate: float = 0.1

    output_type = _passthrough_type

    def build(self, ctx, x, itype):
        if not ctx.training or self.rate <= 0:
            return x, itype
        out = ctx.sd.invoke("gaussian_dropout", [x], {"rate": self.rate},
                            name=ctx.lname("gdrop"))
        return out, itype


@dataclasses.dataclass
class AlphaDropoutLayer(BaseLayer):
    """SELU-compatible dropout (reference: nn/conf/dropout/
    AlphaDropout.java; dropout = RETAIN probability)."""
    dropout: float = 0.95

    output_type = _passthrough_type

    def build(self, ctx, x, itype):
        if not ctx.training or self.dropout >= 1.0:
            return x, itype
        out = ctx.sd.invoke("alpha_dropout", [x], {"p": self.dropout},
                            name=ctx.lname("adrop"))
        return out, itype


@dataclasses.dataclass
class SpatialDropoutLayer(BaseLayer):
    """Whole-channel dropout for cnn/rnn tensors (reference:
    nn/conf/dropout/SpatialDropout.java; dropout = RETAIN prob)."""
    dropout: float = 0.9

    output_type = _passthrough_type

    def build(self, ctx, x, itype):
        if not ctx.training or self.dropout >= 1.0:
            return x, itype
        if itype.kind in ("cnn", "cnn3d"):
            axis = -1 if ctx.cnn_format.endswith("C") else 1
        else:
            axis = -1                       # (B, T, C) sequences
        out = ctx.sd.invoke("spatial_dropout", [x],
                            {"p": self.dropout, "channel_axis": axis},
                            name=ctx.lname("sdrop"))
        return out, itype


for _cls in [GaussianNoiseLayer, GaussianDropoutLayer, AlphaDropoutLayer,
             SpatialDropoutLayer]:
    LAYER_TYPES[_cls.__name__] = _cls
