"""Layer configuration classes.

Reference parity: org.deeplearning4j.nn.conf.layers.* (DenseLayer,
ConvolutionLayer, SubsamplingLayer, BatchNormalization, LSTM,
EmbeddingLayer, OutputLayer, GlobalPoolingLayer, ActivationLayer,
DropoutLayer, LossLayer, …) and nn.conf.inputs.InputType.

TPU-native redesign: the reference implements each layer TWICE — a config
class plus an imperative forward/backprop impl in nn/layers/* built from
INDArray calls with hand-derived gradients. Here a layer config has ONE
``build`` method that records ops into the shared SameDiff graph; backprop
comes from jax.grad of the whole graph, and XLA fuses across layer
boundaries (the reference's per-layer workspaces + cuDNN helper hooks have
no analogue: fusion and memory planning are the compiler's job).

Layout conventions (TPU-first, diverging from the reference where its
layout is CUDA-idiomatic): CNN = NCHW with HWIO kernels (XLA-native),
RNN = (batch, time, features) — the reference's NCW RNN format is a
cuDNN-ism; time-minor keeps the feature dim contiguous for the MXU.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.weights import init_weights


# ----------------------------------------------------------------------
# InputType (reference: nn/conf/inputs/InputType)
@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str                      # "ff" | "cnn" | "cnn3d" | "rnn" | "ids"
    dims: Tuple[int, ...]          # ff: (n,); cnn: (c, h, w);
    #                                cnn3d: (c, d, h, w);
    #                                rnn: (features, timesteps); ids: (t,)

    @staticmethod
    def feed_forward(n: int) -> "InputType":
        return InputType("ff", (int(n),))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", (int(channels), int(height), int(width)))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        """Volumetric data, placeholder (B, C, D, H, W) (reference:
        InputType.convolutional3D)."""
        return InputType("cnn3d", (int(channels), int(depth), int(height),
                                   int(width)))

    @staticmethod
    def recurrent(size: int, timesteps: int = -1) -> "InputType":
        return InputType("rnn", (int(size), int(timesteps)))

    @property
    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.dims[0]
        if self.kind in ("cnn", "cnn3d"):
            return int(np.prod(self.dims))
        raise ValueError(f"cannot flatten {self}")

    def placeholder_shape(self) -> Tuple[int, ...]:
        if self.kind == "ff":
            return (-1, self.dims[0])
        if self.kind in ("cnn", "cnn3d"):
            return (-1,) + self.dims
        if self.kind == "rnn":
            return (-1, self.dims[1], self.dims[0])  # (B, T, C)
        raise ValueError(self.kind)

    def to_json(self):
        return {"kind": self.kind, "dims": list(self.dims)}

    @staticmethod
    def from_json(d):
        return InputType(d["kind"], tuple(d["dims"]))


def _as_pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_out(size: int, k: int, s: int, mode: str, d: int = 1) -> int:
    if mode.upper() == "SAME":
        out = -(-size // s)
    else:
        k_eff = (k - 1) * d + 1
        out = (size - k_eff) // s + 1
    if out < 1:
        # config-time validation (reference: InputTypeUtil.getOutputType*
        # throwing DL4JInvalidConfigException): a collapsed spatial dim
        # must fail HERE with layer math, not as a cryptic zero-dim
        # reshape inside the compiled graph
        raise ValueError(
            f"layer output spatial size {out} < 1 (input {size}, kernel "
            f"{k}, stride {s}, dilation {d}, mode {mode}): the network is "
            f"deeper/stride-ier than the input size supports")
    return out


def _pad_mode(mode: str) -> str:
    """ConvolutionMode → XLA padding string (reference: ConvolutionMode
    {Same, Truncate, Strict, Causal}; Truncate/Strict share the VALID
    output formula — the reference differs only in whether it *errors* on
    non-exact sizes, which static XLA shapes make moot)."""
    m = mode.upper()
    if m == "SAME":
        return "SAME"
    if m in ("VALID", "TRUNCATE", "STRICT"):
        return "VALID"
    raise ValueError(f"unsupported convolution_mode {mode!r} "
                     f"(use Same/Truncate/Strict/Valid)")


# ----------------------------------------------------------------------
class BaseLayer:
    """Common layer contract. Subclasses are dataclasses; ``build`` records
    the layer's ops into ``sd`` and returns (output var, output InputType)."""

    # subclass dataclass fields double as serde schema
    def build(self, ctx: "BuildContext", x, itype: InputType):
        raise NotImplementedError

    def output_type(self, itype: InputType) -> InputType:
        raise NotImplementedError

    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d

    # legacy field renames: {class name: {old json key: new field name}}
    _FIELD_ALIASES = {"TransformerEncoderLayer": {"dropout": "drop_prob"}}

    @staticmethod
    def from_json(d: dict) -> "BaseLayer":
        d = dict(d)
        cls = LAYER_TYPES[d.pop("@class")]
        if hasattr(cls, "_from_json_fields"):   # nested-layer configs
            return cls._from_json_fields(d)
        for old, new in BaseLayer._FIELD_ALIASES.get(cls.__name__, {}).items():
            if old in d and new not in d:
                d[new] = d.pop(old)
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in d:
                v = d[f.name]
                kw[f.name] = tuple(v) if isinstance(v, list) else v
        return cls(**kw)


@dataclasses.dataclass
class BuildContext:
    """Carries graph + init RNG + train/infer mode through layer builds."""
    sd: object                      # SameDiff
    rng: np.random.Generator
    training: bool
    dtype: str = "float32"
    idx: int = 0                    # current layer index
    prefix: Optional[str] = None    # vertex name (ComputationGraph builds)
    labels_var: object = None       # labels placeholder (for loss heads)
    output_var: object = None       # set by the output layer
    loss_var: object = None         # set by the output layer
    # TBPTT mode: when set, recurrent layers carry their hidden state in
    # persistent state vars of shape (tbptt_batch, units) instead of
    # in-graph zeros — the train step's stop_gradient on state-var inputs
    # IS the truncation (reference: MultiLayerNetwork.doTruncatedBPTT:2083)
    tbptt_batch: Optional[int] = None
    rnn_state_vars: list = dataclasses.field(default_factory=list)
    # runtime layout for cnn tensors. InputType dims stay (c, h, w) and the
    # network's EXTERNAL contract stays NCHW (reference convention; users
    # feed/receive NCHW) — but internally the compiled graph runs NHWC:
    # logical-NCHW convs on TPU force physical transposes of every
    # activation, measured 12x slower than the same net in NHWC (see
    # PROFILE.md). One permute at the network input; zero in the body.
    cnn_format: str = "NHWC"

    def lname(self, kind: str) -> str:
        """Parameter/op name stem: vertex name in graph builds, layer index
        in sequential builds (reference: param keys '0_W' vs 'dense1_W')."""
        return self.prefix if self.prefix else f"layer{self.idx}_{kind}"

    def param(self, name: str, shape, scheme: str):
        """Create (or look up, for the second graph build) a parameter."""
        return self.sd.var(name, value=init_weights(scheme, tuple(shape),
                                                    self.rng),
                           dtype=self.dtype)

    def state(self, name: str, value):
        return self.sd.state_var(name, np.asarray(value), dtype=self.dtype)


def _rnn_initial_states(ctx: BuildContext, lname: str, x, units: int,
                        names=("h0",)):
    """Initial recurrent state(s): in-graph zeros normally; persistent
    zero-initialized state vars in TBPTT mode (reset per sequence batch by
    fit_tbptt, carried across chunks by the train step)."""
    outs = []
    for nm in names:
        if ctx.tbptt_batch:
            sv = ctx.state(f"{lname}_{nm}_state",
                           np.zeros((ctx.tbptt_batch, units)))
            ctx.rnn_state_vars.append(sv.name)
            outs.append(sv)
        else:
            outs.append(ctx.sd.invoke("rnn_init_state", [x],
                                      {"units": units}, name=f"{lname}_{nm}"))
    return outs


def _rnn_carry_states(ctx: BuildContext, pairs):
    """Declare state-var carries (state_var, final_state_var) in TBPTT
    mode; no-op otherwise."""
    if ctx.tbptt_batch:
        for sv, fv in pairs:
            ctx.sd.update_state(sv, fv)


def _maybe_dropout(ctx: BuildContext, x, p: float, lname: str):
    """Input dropout (reference: BaseLayer.dropOut — p = retain prob)."""
    if p and 0 < p < 1 and ctx.training:
        return ctx.sd.invoke("dropout", [x], {"p": p}, name=f"{lname}_drop")
    return x


# ----------------------------------------------------------------------
@dataclasses.dataclass
class DenseLayer(BaseLayer):
    """Fully connected (reference: nn/conf/layers/DenseLayer + the mmul in
    layers/BaseLayer.preOutput, BaseLayer.java:300-322)."""
    n_out: int = 0
    activation: str = "relu"
    weight_init: str = "XAVIER"
    bias_init: float = 0.0
    dropout: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        if itype.kind == "rnn":
            # per-timestep dense — the reference reaches the same semantics
            # via the RnnToFeedForward/FeedForwardToRnn preprocessor pair
            # (merge time into batch, dense, split back); here the matmul
            # broadcasts over (B, T) directly
            return InputType.recurrent(self.n_out, itype.dims[1])
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("dense")
        n_in = itype.dims[0] if itype.kind == "rnn" else itype.flat_size
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w = ctx.param(f"{lname}_W", (n_in, self.n_out), self.weight_init)
        z = x.mmul(w, name=f"{lname}_mm")
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            z = z.add(b, name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class EmbeddingLayer(BaseLayer):
    """Index → vector lookup (reference: nn/conf/layers/EmbeddingLayer;
    native op generic/nn/embedding_lookup)."""
    n_in: int = 0        # vocabulary size
    n_out: int = 0
    weight_init: str = "XAVIER"

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("embedding")
        if itype.flat_size != 1:
            raise ValueError(
                f"EmbeddingLayer expects a single index column "
                f"(InputType.feed_forward(1)); got {itype} — the reference "
                f"EmbeddingLayer validates nIn the same way")
        table = ctx.param(f"{lname}_W", (self.n_in, self.n_out),
                          self.weight_init)
        ids = ctx.sd.invoke("reshape", [x], {"shape": (-1,)},
                            name=f"{lname}_ids")
        ids = ids.cast("int32")
        out = ctx.sd.invoke("embedding_lookup", [table, ids], {},
                            name=f"{lname}_out")
        return out, self.output_type(itype)


@dataclasses.dataclass
class ConvolutionLayer(BaseLayer):
    """2D convolution (reference: nn/conf/layers/ConvolutionLayer; native
    conv2d, generic/nn/convo/conv2d.cpp:39). NCHW / HWIO."""
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    convolution_mode: str = "SAME"       # reference ConvolutionMode Same/Truncate
    dilation: Tuple[int, int] = (1, 1)
    activation: str = "identity"
    weight_init: str = "RELU"
    bias_init: float = 0.0
    has_bias: bool = True
    dropout: float = 0.0

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride)
        dh, dw = _as_pair(self.dilation)
        return InputType("cnn", (self.n_out,
                                 _conv_out(h, kh, sh, self.convolution_mode, dh),
                                 _conv_out(w, kw, sw, self.convolution_mode, dw)))

    def build(self, ctx, x, itype):
        lname = ctx.lname("conv")
        c_in = itype.dims[0]
        kh, kw = _as_pair(self.kernel_size)
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w = ctx.param(f"{lname}_W", (kh, kw, c_in, self.n_out),
                      self.weight_init)
        inputs = [x, w]
        attrs = {"strides": _as_pair(self.stride),
                 "padding": _pad_mode(self.convolution_mode),
                 "dilation": _as_pair(self.dilation),
                 "data_format": ctx.cnn_format}
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            inputs.append(b)
        z = ctx.sd.invoke("conv2d", inputs, attrs, name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class SubsamplingLayer(BaseLayer):
    """Pooling (reference: nn/conf/layers/SubsamplingLayer, PoolingType
    MAX/AVG/PNORM; native maxpool2d/avgpool2d/pnormpool2d)."""
    pooling_type: str = "MAX"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Optional[Tuple[int, int]] = None
    convolution_mode: str = "VALID"
    pnorm: int = 2

    def output_type(self, itype):
        c, h, w = itype.dims
        kh, kw = _as_pair(self.kernel_size)
        sh, sw = _as_pair(self.stride or self.kernel_size)
        return InputType("cnn", (c,
                                 _conv_out(h, kh, sh, self.convolution_mode),
                                 _conv_out(w, kw, sw, self.convolution_mode)))

    def build(self, ctx, x, itype):
        lname = ctx.lname("pool")
        op = {"MAX": "max_pool2d", "AVG": "avg_pool2d",
              "PNORM": "pnorm_pool2d"}[self.pooling_type.upper()]
        attrs = {"kernel": _as_pair(self.kernel_size),
                 "strides": _as_pair(self.stride or self.kernel_size),
                 "padding": _pad_mode(self.convolution_mode),
                 "data_format": ctx.cnn_format}
        if self.pooling_type.upper() == "PNORM":
            attrs["pnorm"] = self.pnorm
        out = ctx.sd.invoke(op, [x], attrs, name=lname)
        return out, self.output_type(itype)


@dataclasses.dataclass
class BatchNormalization(BaseLayer):
    """Batch norm (reference: nn/conf/layers/BatchNormalization — 'decay' is
    the running-average momentum; layers/normalization/BatchNormalization).
    Running stats live as SameDiff state vars updated inside the step."""
    decay: float = 0.9
    eps: float = 1e-5

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("bn")
        n = itype.dims[0]
        gamma = ctx.sd.var(f"{lname}_gamma", value=np.ones((n,)),
                           dtype=ctx.dtype)
        beta = ctx.sd.var(f"{lname}_beta", value=np.zeros((n,)),
                          dtype=ctx.dtype)
        mean = ctx.state(f"{lname}_mean", np.zeros((n,)))
        var = ctx.state(f"{lname}_var", np.ones((n,)))
        # feature axis: 2 for (B, T, C) sequences; -1 for NHWC cnn tensors;
        # 1 for NCHW / (B, n)
        if itype.kind == "rnn":
            axis = 2
        elif itype.kind in ("cnn", "cnn3d") and ctx.cnn_format.endswith("C"):
            axis = -1
        else:
            axis = 1
        if ctx.training:
            out, new_mean, new_var = ctx.sd.invoke(
                "batchnorm_train", [x, gamma, beta, mean, var],
                {"momentum": self.decay, "epsilon": self.eps, "axis": axis},
                name=lname, n_outputs=3)
            ctx.sd.update_state(mean, new_mean)
            ctx.sd.update_state(var, new_var)
        else:
            out = ctx.sd.invoke(
                "batchnorm", [x, mean, var, gamma, beta],
                {"epsilon": self.eps, "axis": axis}, name=lname)
        return out, itype


@dataclasses.dataclass
class ActivationLayer(BaseLayer):
    """Standalone activation (reference: nn/conf/layers/ActivationLayer)."""
    activation: str = "relu"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        return (apply_activation(ctx.sd, x, self.activation,
                                 ctx.lname("act")), itype)


@dataclasses.dataclass
class DropoutLayer(BaseLayer):
    """Standalone dropout (reference: nn/conf/layers/DropoutLayer;
    p = retain probability, matching nn/conf/dropout/Dropout)."""
    dropout: float = 0.5

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        lname = ctx.lname("dropout")
        if ctx.training and 0 < self.dropout < 1:
            x = ctx.sd.invoke("dropout", [x], {"p": self.dropout}, name=lname)
        return x, itype


@dataclasses.dataclass
class LSTMLayer(BaseLayer):
    """LSTM over sequences (reference: nn/conf/layers/LSTM +
    layers/recurrent/LSTMHelpers; native generic/recurrent/lstmLayer.cpp).
    Input/output layout (B, T, C); lax.scan compiles the recurrence into
    one XLA While loop."""
    n_out: int = 0
    weight_init: str = "XAVIER"
    forget_gate_bias_init: float = 1.0
    return_sequences: bool = True
    dropout: float = 0.0

    def output_type(self, itype):
        if self.return_sequences:
            return InputType.recurrent(self.n_out, itype.dims[1])
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("lstm")
        n_in = itype.dims[0]
        u = self.n_out
        x = _maybe_dropout(ctx, x, self.dropout, lname)
        w_ih = ctx.param(f"{lname}_Wih", (n_in, 4 * u), self.weight_init)
        w_hh = ctx.param(f"{lname}_Whh", (u, 4 * u), self.weight_init)
        b0 = np.zeros((4 * u,))
        b0[u:2 * u] = self.forget_gate_bias_init  # [i, f, g, o] gate order
        b = ctx.sd.var(f"{lname}_b", value=b0, dtype=ctx.dtype)
        h0, c0 = _rnn_initial_states(ctx, lname, x, u, ("h0", "c0"))
        out, hT, cT = ctx.sd.invoke(
            "lstm_layer", [x, h0, c0, w_ih, w_hh, b],
            {"time_major": False, "return_sequences": self.return_sequences},
            name=lname, n_outputs=3)
        _rnn_carry_states(ctx, [(h0, hT), (c0, cT)])
        result = out if self.return_sequences else hT
        return result, self.output_type(itype)


@dataclasses.dataclass
class GlobalPoolingLayer(BaseLayer):
    """Global pooling over spatial or time dims (reference:
    nn/conf/layers/GlobalPoolingLayer, PoolingType MAX/AVG/SUM)."""
    pooling_type: str = "AVG"

    def output_type(self, itype):
        if itype.kind in ("cnn", "cnn3d", "rnn"):
            return InputType.feed_forward(itype.dims[0])
        raise ValueError("GlobalPoolingLayer needs cnn or rnn input "
                         "(reference GlobalPoolingLayer rejects FF input too)")

    def build(self, ctx, x, itype):
        self.output_type(itype)  # validate input kind
        lname = ctx.lname("gpool")
        if itype.kind in ("cnn", "cnn3d") and ctx.cnn_format.endswith("C"):
            axis = {"cnn": (1, 2), "cnn3d": (1, 2, 3)}[itype.kind]
        else:
            axis = {"cnn": (2, 3), "cnn3d": (2, 3, 4), "rnn": (1,)}[itype.kind]
        opname = {"AVG": "reduce_mean", "MAX": "reduce_max",
                  "SUM": "reduce_sum"}[self.pooling_type.upper()]
        out = ctx.sd.invoke(opname, [x], {"axis": axis}, name=lname)
        return out, self.output_type(itype)


_LOSS_OPS = {
    "MCXENT": "softmax_cross_entropy",           # reference LossMCXENT
    "NEGATIVELOGLIKELIHOOD": "softmax_cross_entropy",
    "MSE": "mean_sqerr_loss",
    "L1": "absolute_difference_loss",
    "XENT": "sigm_cross_entropy",                # binary cross-entropy on logits
    "HINGE": "hinge_loss",
    "SQUARED_HINGE": "squared_hinge_loss",
    "POISSON": "poisson_loss",
    "KL_DIVERGENCE": "kl_divergence_loss",
    "COSINE_PROXIMITY": "cosine_distance_loss",
}

# losses that fuse the activation and therefore take PRE-activation logits
_FUSED_LOGIT_LOSSES = ("softmax_cross_entropy", "sigm_cross_entropy")


def _attach_loss_head(ctx, z, out, loss_function: str):
    """Wire a loss head: pick the loss op, feed it logits (fused losses)
    or activations, mark it, and record output/loss on the build context.
    Shared by OutputLayer, LossLayer, RnnOutputLayer."""
    ctx.output_var = out
    loss_op = _LOSS_OPS[loss_function.upper()]
    loss_in = z if loss_op in _FUSED_LOGIT_LOSSES else out
    loss = ctx.sd.invoke(loss_op, [loss_in, ctx.labels_var], {}, name="loss")
    loss.mark_as_loss()
    ctx.loss_var = loss
    return loss


@dataclasses.dataclass
class OutputLayer(BaseLayer):
    """Dense + loss head (reference: nn/conf/layers/OutputLayer with
    LossFunction; loss computed from PRE-activation logits where the loss
    fuses the activation — MCXENT+softmax, XENT+sigmoid — matching the
    reference's fused loss implementations)."""
    n_out: int = 0
    loss_function: str = "MCXENT"
    activation: str = "softmax"
    weight_init: str = "XAVIER"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def build(self, ctx, x, itype):
        lname = ctx.lname("out")
        n_in = itype.flat_size
        w = ctx.param(f"{lname}_W", (n_in, self.n_out), self.weight_init)
        z = x.mmul(w, name=f"{lname}_mm")
        if self.has_bias:
            b = ctx.sd.var(f"{lname}_b",
                           value=np.full((self.n_out,), self.bias_init),
                           dtype=ctx.dtype)
            z = z.add(b, name=f"{lname}_z")
        out = apply_activation(ctx.sd, z, self.activation, lname)
        _attach_loss_head(ctx, z, out, self.loss_function)
        return out, self.output_type(itype)


@dataclasses.dataclass
class LossLayer(BaseLayer):
    """Loss without params (reference: nn/conf/layers/LossLayer)."""
    loss_function: str = "MSE"
    activation: str = "identity"

    def output_type(self, itype):
        return itype

    def build(self, ctx, x, itype):
        out = apply_activation(ctx.sd, x, self.activation, ctx.lname("act"))
        _attach_loss_head(ctx, x, out, self.loss_function)
        return out, itype


LAYER_TYPES: Dict[str, type] = {c.__name__: c for c in [
    DenseLayer, EmbeddingLayer, ConvolutionLayer, SubsamplingLayer,
    BatchNormalization, ActivationLayer, DropoutLayer, LSTMLayer,
    GlobalPoolingLayer, OutputLayer, LossLayer,
]}
