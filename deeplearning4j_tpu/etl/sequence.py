"""Sequence ETL: grouping rows into time series and transforming them.

Reference parity: org.datavec.api.transform.sequence.* —
ConvertToSequence (group by key, order by time/comparator),
ConvertFromSequence, offset (SequenceOffsetTransform), moving window
(ReduceSequenceByWindowTransform / TimeWindowFunction), trim
(SequenceTrimTransform), split (SequenceSplitTimeSeparation).

TPU-native redesign: a sequence set is ``(schema, [columnar dict per
sequence])`` and the terminal export is ``sequences_to_arrays`` — a
padded dense [N, T, F] batch + [N, T] mask, the layout RNN/attention
training on TPU actually consumes (static shapes for XLA; the reference
keeps ragged List<List<Writable>> all the way down and pads in the
RecordReaderMultiDataSetIterator instead).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.etl.relational import _key_ids
from deeplearning4j_tpu.etl.schema import FLOAT, INTEGER, Schema

SequenceData = List[Dict[str, np.ndarray]]


def convert_to_sequence(schema: Schema, cols: Dict[str, np.ndarray],
                        key_column: str, time_column: Optional[str] = None
                        ) -> Tuple[List, SequenceData]:
    """Group rows by key, each group sorted by time (reference:
    sequence/ConvertToSequence.java). Returns (keys, sequences); groups
    appear in first-occurrence order."""
    schema.column(key_column)
    keys = _key_ids(cols, [key_column])
    seen: Dict[tuple, int] = {}
    groups: List[List[int]] = []
    order: List = []
    for i, k in enumerate(keys):
        if k not in seen:
            seen[k] = len(groups)
            groups.append([])
            order.append(k[0])
        groups[seen[k]].append(i)
    out: SequenceData = []
    for rows in groups:
        idx = np.asarray(rows, np.int64)
        if time_column is not None:
            t = cols[time_column][idx]
            idx = idx[np.argsort(t, kind="stable")]
        out.append({name: cols[name][idx] for name in schema.names()})
    return order, out


def convert_from_sequence(sequences: SequenceData) -> Dict[str, np.ndarray]:
    """Flatten sequences back to one columnar table (reference:
    sequence/ConvertFromSequence)."""
    if not sequences:
        return {}
    return {k: np.concatenate([s[k] for s in sequences])
            for k in sequences[0]}


def offset_column(sequences: SequenceData, column: str, offset: int,
                  new_name: Optional[str] = None,
                  trim: bool = True) -> SequenceData:
    """Shift ``column`` by ``offset`` steps within each sequence
    (reference: sequence/transform/SequenceOffsetTransform.java). Positive
    offset makes row t see the value from t-offset (lag); negative is a
    lead. With trim=True, rows without a shifted value are dropped."""
    if offset == 0:
        return sequences
    name = new_name or f"{column}_offset({offset})"
    out: SequenceData = []
    for s in sequences:
        n = len(s[column])
        k = abs(offset)
        if n <= k:
            if trim:
                continue
            k = n
        shifted = np.roll(s[column], offset)
        if trim:
            sl = slice(k, None) if offset > 0 else slice(None, n - k)
            t = {c: v[sl] for c, v in s.items()}
            t[name] = shifted[sl]
        else:
            t = dict(s)
            fill = shifted.copy()
            if offset > 0:
                fill[:k] = s[column][0]
            else:
                fill[n - k:] = s[column][-1]
            t[name] = fill
        out.append(t)
    return out


def trim_sequence(sequences: SequenceData, num_steps: int,
                  from_start: bool = True) -> SequenceData:
    """(reference: sequence/trim/SequenceTrimTransform.java)"""
    sl = slice(num_steps, None) if from_start else slice(None, -num_steps)
    return [{k: v[sl] for k, v in s.items()} for s in sequences
            if len(next(iter(s.values()))) > num_steps]


def split_sequence_on_gap(sequences: SequenceData, time_column: str,
                          max_gap: int) -> SequenceData:
    """Split a sequence wherever consecutive time values differ by more
    than max_gap (reference: sequence/split/SequenceSplitTimeSeparation)."""
    out: SequenceData = []
    for s in sequences:
        t = s[time_column]
        if len(t) == 0:
            continue
        cut = np.nonzero(np.diff(t.astype(np.float64)) > max_gap)[0] + 1
        for part in np.split(np.arange(len(t)), cut):
            out.append({k: v[part] for k, v in s.items()})
    return out


def reduce_sequence_by_window(sequences: SequenceData, column: str,
                              window: int, op: str = "mean",
                              stride: Optional[int] = None) -> SequenceData:
    """Tumbling/sliding window reduction over one column (reference:
    sequence/window + ReduceSequenceByWindowTransform). Other columns take
    the value at each window's last step."""
    stride = stride or window
    fns: Dict[str, Callable] = {"mean": np.mean, "sum": np.sum,
                                "min": np.min, "max": np.max,
                                "stdev": lambda v: np.std(v, ddof=1)
                                if len(v) > 1 else 0.0}
    if op not in fns:
        raise ValueError(f"unknown window op {op!r}")
    out: SequenceData = []
    for s in sequences:
        n = len(s[column])
        starts = list(range(0, max(n - window + 1, 1), stride))
        ends = [min(st + window, n) for st in starts]
        t = {k: v[[e - 1 for e in ends]] for k, v in s.items()}
        t[f"{op}({column},w={window})"] = np.asarray(
            [fns[op](s[column][st:e].astype(np.float64))
             for st, e in zip(starts, ends)], np.float32)
        out.append(t)
    return out


def sequences_to_arrays(sequences: SequenceData,
                        feature_columns: Sequence[str],
                        label_column: Optional[str] = None,
                        max_len: Optional[int] = None
                        ) -> Tuple[np.ndarray, np.ndarray,
                                   Optional[np.ndarray]]:
    """Terminal export: padded [N, T, F] features + [N, T] mask (+ [N, T]
    labels). This is where ragged sequences become the static-shaped
    batch XLA requires; the reference does the equivalent padding in
    RecordReaderMultiDataSetIterator with ALIGN_END/mask arrays."""
    if not sequences:
        raise ValueError("no sequences")
    lens = [len(s[feature_columns[0]]) for s in sequences]
    t_max = max_len or max(lens)
    n, f = len(sequences), len(feature_columns)
    feats = np.zeros((n, t_max, f), np.float32)
    mask = np.zeros((n, t_max), np.float32)
    labels = np.zeros((n, t_max), np.float32) if label_column else None
    for i, s in enumerate(sequences):
        t = min(lens[i], t_max)
        for j, c in enumerate(feature_columns):
            feats[i, :t, j] = s[c][:t].astype(np.float32)
        mask[i, :t] = 1.0
        if label_column:
            labels[i, :t] = s[label_column][:t].astype(np.float32)
    return feats, mask, labels
