"""RecordReaderDataSetIterator: records -> training batches.

Reference parity: deeplearning4j-datavec-iterators
RecordReaderDataSetIterator.java:54 — wraps a RecordReader (+ optional
TransformProcess), splits each record into features/labels (label column
index, one-hot for classification), and yields minibatches a network's
fit() consumes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.etl.records import ImageRecordReader, RecordReader
from deeplearning4j_tpu.etl.schema import Schema
from deeplearning4j_tpu.etl.transform import TransformProcess


class RecordReaderDataSetIterator:
    """Tabular records -> (features, labels) batches.

    label_column: name (with schema/transform) or index of the label.
    num_classes: one-hot width for classification; None = regression
    (label kept as float column).
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_column=None, num_classes: Optional[int] = None,
                 transform_process: Optional[TransformProcess] = None,
                 schema: Optional[Schema] = None,
                 shuffle: bool = False, seed: Optional[int] = None):
        self._reader = reader
        self._tp = transform_process
        self._schema = schema or (transform_process.initial_schema
                                  if transform_process else None)
        self._batch = int(batch_size)
        self._label = label_column
        self._num_classes = num_classes
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._cache = None

    def reset(self):
        if hasattr(self._reader, "reset"):
            self._reader.reset()
        self._cache = None

    def _matrix(self):
        if self._cache is not None:
            return self._cache
        if self._tp is not None:
            cols = self._tp.execute_columnar(self._reader)
            names = self._tp.final_schema().names()
        elif self._schema is not None:
            from deeplearning4j_tpu.etl.schema import columnar
            cols = columnar(self._schema, list(self._reader))
            names = self._schema.names()
        else:
            rows = [list(map(float, r)) for r in self._reader]
            arr = np.asarray(rows, np.float32)
            names = [str(i) for i in range(arr.shape[1])]
            cols = {n: arr[:, i] for i, n in enumerate(names)}
        if isinstance(self._label, int):
            label_name = names[self._label]
        else:
            label_name = self._label
        feat_names = [n for n in names if n != label_name]
        feats = np.stack([cols[n].astype(np.float32) for n in feat_names],
                         axis=1)
        if label_name is None:
            labels = None
        else:
            lab = cols[label_name]
            if self._num_classes is not None:
                labels = np.eye(self._num_classes, dtype=np.float32)[
                    lab.astype(np.int64)]
            else:
                labels = lab.astype(np.float32).reshape(-1, 1)
        self._cache = (feats, labels)
        return self._cache

    def __iter__(self):
        feats, labels = self._matrix()
        idx = np.arange(len(feats))
        if self._shuffle:
            self._rng.shuffle(idx)
        # final partial batch included (reference
        # RecordReaderDataSetIterator behavior)
        for i in range(0, len(idx), self._batch):
            sel = idx[i:i + self._batch]
            yield (feats[sel], labels[sel] if labels is not None
                   else feats[sel])

    def all_data(self):
        return self._matrix()


class ImageRecordReaderDataSetIterator:
    """Image-directory records -> (NCHW float images, one-hot labels)
    batches (reference: RecordReaderDataSetIterator over an
    ImageRecordReader + ImagePreProcessingScaler semantics via ``scale``).
    """

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 scale: float = 1.0 / 255.0, shuffle: bool = False,
                 seed: Optional[int] = None):
        self._reader = reader
        self._batch = int(batch_size)
        self._scale = scale
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._cache = None

    @property
    def labels(self) -> List[str]:
        return self._reader.labels

    def num_classes(self) -> int:
        return len(self._reader.labels)

    def reset(self):
        self._cache = None

    def _load_all(self):
        if self._cache is not None:
            return self._cache
        table = {lab: i for i, lab in enumerate(self._reader.labels)}
        xs, ys = [], []
        for img, lab in self._reader:
            xs.append(np.transpose(img, (2, 0, 1)) * self._scale)  # HWC->CHW
            ys.append(table[lab])
        X = np.stack(xs).astype(np.float32)
        Y = np.eye(len(table), dtype=np.float32)[np.asarray(ys, np.int64)]
        self._cache = (X, Y)
        return self._cache

    def __iter__(self):
        X, Y = self._load_all()
        idx = np.arange(len(X))
        if self._shuffle:
            self._rng.shuffle(idx)
        for i in range(0, len(idx), self._batch):   # incl. partial tail
            sel = idx[i:i + self._batch]
            yield X[sel], Y[sel]
