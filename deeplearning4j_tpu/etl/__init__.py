"""ETL subsystem (reference: datavec — datavec-api RecordReader/
TransformProcess/Schema + datavec-data-image, SURVEY.md §2.4).

TPU-native redesign: transforms execute as vectorized numpy passes over
columnar arrays (not a row-of-Writables interpreter) and feed
device-stacked batches; images decode to HWC float32 with no native
binding layer.
"""
from deeplearning4j_tpu.etl.schema import (
    CATEGORICAL, FLOAT, INTEGER, STRING, TIME, ColumnMeta, Schema,
    columnar, to_rows)
from deeplearning4j_tpu.etl.records import (
    CollectionRecordReader, CSVRecordReader, ImageRecordReader,
    LineRecordReader, RecordReader)
from deeplearning4j_tpu.etl.transform import (
    ColumnAnalysis, ColumnQuality, DataAnalysis, DataQualityAnalysis,
    TransformProcess, analyze, analyze_quality)
from deeplearning4j_tpu.etl.iterator import (
    ImageRecordReaderDataSetIterator, RecordReaderDataSetIterator)
from deeplearning4j_tpu.etl.relational import (
    FULL_OUTER, INNER, LEFT_OUTER, RIGHT_OUTER, Join, Reducer)
from deeplearning4j_tpu.etl.image_transform import (
    BoxImageTransform, CropImageTransform, FlipImageTransform,
    ImageTransform, PipelineImageTransform, RandomCropTransform,
    ResizeImageTransform, RotateImageTransform, ScaleImageTransform)
from deeplearning4j_tpu.etl.sequence import (
    convert_from_sequence, convert_to_sequence, offset_column,
    reduce_sequence_by_window, sequences_to_arrays, split_sequence_on_gap,
    trim_sequence)

__all__ = [
    "Schema", "ColumnMeta", "columnar", "to_rows",
    "INTEGER", "FLOAT", "CATEGORICAL", "STRING", "TIME",
    "RecordReader", "CSVRecordReader", "LineRecordReader",
    "CollectionRecordReader", "ImageRecordReader",
    "TransformProcess", "analyze", "DataAnalysis", "ColumnAnalysis",
    "analyze_quality", "DataQualityAnalysis", "ColumnQuality",
    "RecordReaderDataSetIterator", "ImageRecordReaderDataSetIterator",
    "Join", "Reducer", "INNER", "LEFT_OUTER", "RIGHT_OUTER", "FULL_OUTER",
    "convert_to_sequence", "convert_from_sequence", "offset_column",
    "trim_sequence", "split_sequence_on_gap", "reduce_sequence_by_window",
    "sequences_to_arrays",
    "ImageTransform", "FlipImageTransform", "RotateImageTransform",
    "CropImageTransform", "RandomCropTransform", "ResizeImageTransform",
    "ScaleImageTransform", "BoxImageTransform", "PipelineImageTransform",
]
