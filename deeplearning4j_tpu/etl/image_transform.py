"""Image transform pipeline: augmentation over HWC float32 arrays.

Reference parity: org.datavec.image.transform — ImageTransform
implementations (FlipImageTransform, RotateImageTransform,
CropImageTransform / RandomCropTransform, ResizeImageTransform,
ScaleImageTransform, WarpImageTransform's role, ColorConversion's
brightness/contrast role, BoxImageTransform's pad role) composed by
PipelineImageTransform with per-transform probabilities.

TPU-native notes: transforms run on host numpy over HWC float32 (the
decode format) — augmentation belongs in the input pipeline, not the
compiled graph; everything is vectorized whole-image numpy (no per-pixel
loops, no OpenCV binding layer)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class ImageTransform:
    """One augmentation step (reference: transform/ImageTransform)."""

    def transform(self, img: np.ndarray,
                  rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, img, rng=None):
        return self.transform(np.asarray(img, np.float32),
                              rng or np.random.default_rng())


class FlipImageTransform(ImageTransform):
    """(reference: transform/FlipImageTransform — mode: 0 vertical,
    1 horizontal, -1 both; None = random horizontal)."""

    def __init__(self, mode: Optional[int] = 1):
        self.mode = mode

    def transform(self, img, rng):
        mode = self.mode
        if mode is None:
            if rng.random() < 0.5:
                return img
            mode = 1
        if mode == 1:
            return img[:, ::-1]
        if mode == 0:
            return img[::-1]
        return img[::-1, ::-1]


class RotateImageTransform(ImageTransform):
    """Right-angle rotation in degrees; random multiple of 90 when angle
    is None (reference: transform/RotateImageTransform — arbitrary-angle
    warps collapse to the right-angle family without an OpenCV layer)."""

    def __init__(self, angle: Optional[int] = 90):
        if angle is not None and angle % 90:
            raise ValueError("host rotation supports multiples of 90°")
        self.angle = angle

    def transform(self, img, rng):
        k = (int(rng.integers(0, 4)) if self.angle is None
             else (self.angle // 90) % 4)
        return np.rot90(img, k=k, axes=(0, 1)).copy()


class CropImageTransform(ImageTransform):
    """Fixed margin crop (reference: transform/CropImageTransform)."""

    def __init__(self, top: int, left: int = None, bottom: int = None,
                 right: int = None):
        self.top = top
        self.left = top if left is None else left
        self.bottom = top if bottom is None else bottom
        self.right = top if right is None else right

    def transform(self, img, rng):
        h, w = img.shape[:2]
        if self.top + self.bottom >= h or self.left + self.right >= w:
            raise ValueError(
                f"crop margins ({self.top},{self.left},{self.bottom},"
                f"{self.right}) consume the whole {h}x{w} image")
        return img[self.top:h - self.bottom, self.left:w - self.right]


class RandomCropTransform(ImageTransform):
    """Crop to (height, width) at a random position (reference:
    transform/RandomCropTransform)."""

    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def transform(self, img, rng):
        h, w = img.shape[:2]
        if h < self.height or w < self.width:
            raise ValueError(f"image {h}x{w} smaller than crop "
                             f"{self.height}x{self.width}")
        i = int(rng.integers(0, h - self.height + 1))
        j = int(rng.integers(0, w - self.width + 1))
        return img[i:i + self.height, j:j + self.width]


class ResizeImageTransform(ImageTransform):
    """Resize to (height, width) (reference:
    transform/ResizeImageTransform) — bilinear via vectorized numpy."""

    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def transform(self, img, rng):
        h, w = img.shape[:2]
        if (h, w) == (self.height, self.width):
            return img
        ys = np.linspace(0, h - 1, self.height)
        xs = np.linspace(0, w - 1, self.width)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        # one gather per corner (np.ix_), no full-width intermediates
        a = img[np.ix_(y0, x0)]
        b = img[np.ix_(y0, x1)]
        c = img[np.ix_(y1, x0)]
        d = img[np.ix_(y1, x1)]
        return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
                + c * wy * (1 - wx) + d * wy * wx).astype(np.float32)


class ScaleImageTransform(ImageTransform):
    """Pixel-value scale/shift (the brightness/contrast role of the
    reference's color transforms)."""

    def __init__(self, scale: float = 1.0, shift: float = 0.0,
                 clip: Optional[Tuple[float, float]] = (0.0, 255.0)):
        self.scale, self.shift, self.clip = scale, shift, clip

    def transform(self, img, rng):
        out = img * self.scale + self.shift
        if self.clip is not None:
            out = np.clip(out, *self.clip)
        return out.astype(np.float32)


class BoxImageTransform(ImageTransform):
    """Pad/crop to a centered (height, width) box (reference:
    transform/BoxImageTransform)."""

    def __init__(self, height: int, width: int, fill: float = 0.0):
        self.height, self.width, self.fill = height, width, fill

    def transform(self, img, rng):
        h, w, c = img.shape
        out = np.full((self.height, self.width, c), self.fill, np.float32)
        ti = max((self.height - h) // 2, 0)
        tj = max((self.width - w) // 2, 0)
        si = max((h - self.height) // 2, 0)
        sj = max((w - self.width) // 2, 0)
        ch = min(h, self.height)
        cw = min(w, self.width)
        out[ti:ti + ch, tj:tj + cw] = img[si:si + ch, sj:sj + cw]
        return out


class PipelineImageTransform(ImageTransform):
    """Sequential pipeline with per-step probabilities (reference:
    transform/PipelineImageTransform — shuffle=False path)."""

    def __init__(self, *steps, seed: Optional[int] = None):
        self.steps: List[Tuple[ImageTransform, float]] = [
            s if isinstance(s, tuple) else (s, 1.0) for s in steps]
        self._rng = np.random.default_rng(seed)

    def transform(self, img, rng=None):
        rng = rng or self._rng
        for t, p in self.steps:
            if p >= 1.0 or rng.random() < p:
                img = t.transform(img, rng)
        return img

    def __call__(self, img, rng=None):
        return self.transform(np.asarray(img, np.float32), rng)
