"""Column schema for record ETL.

Reference parity: org.datavec.api.transform.schema.Schema (datavec-api —
column names + ColumnType {Integer, Long, Double, Float, Categorical,
String, Time, Bytes} with per-column metadata) and its fluent Builder.

TPU-native redesign: columns are numpy-typed and transform execution is
COLUMNAR (vectorized numpy over whole column arrays), not the reference's
row-of-Writables interpreter — rows only exist at the RecordReader
boundary. The type set collapses to what a device pipeline distinguishes:
integer, float, categorical (string values + known vocabulary), string,
time (int64 epoch millis).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

INTEGER = "integer"
FLOAT = "float"
CATEGORICAL = "categorical"
STRING = "string"
TIME = "time"

_NP_OF = {INTEGER: np.int64, FLOAT: np.float32, TIME: np.int64,
          CATEGORICAL: object, STRING: object}


@dataclasses.dataclass
class ColumnMeta:
    name: str
    ctype: str
    categories: Optional[Tuple[str, ...]] = None    # CATEGORICAL only

    def np_dtype(self):
        return _NP_OF[self.ctype]


class Schema:
    """Ordered column metadata (reference: transform/schema/Schema.java)."""

    def __init__(self, columns: Sequence[ColumnMeta]):
        self.columns: List[ColumnMeta] = list(columns)
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")

    # -- queries ---------------------------------------------------------
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> ColumnMeta:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"no column {name!r}; have {self.names()}")

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def has(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    def num_columns(self) -> int:
        return len(self.columns)

    def __repr__(self):
        cols = ", ".join(f"{c.name}:{c.ctype}" for c in self.columns)
        return f"Schema({cols})"

    # -- serde ------------------------------------------------------------
    def to_json(self) -> dict:
        return {"columns": [{"name": c.name, "type": c.ctype,
                             "categories": list(c.categories)
                             if c.categories else None}
                            for c in self.columns]}

    @staticmethod
    def from_json(d: dict) -> "Schema":
        return Schema([ColumnMeta(c["name"], c["type"],
                                  tuple(c["categories"])
                                  if c.get("categories") else None)
                       for c in d["columns"]])

    # -- builder (reference: Schema.Builder) ------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_integer(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, INTEGER)); return self

        def add_column_float(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, FLOAT)); return self

        add_column_double = add_column_float

        def add_column_categorical(self, name: str,
                                   *categories: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, CATEGORICAL,
                                         tuple(categories))); return self

        def add_column_string(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, STRING)); return self

        def add_column_time(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, TIME)); return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()


def columnar(schema: Schema, rows: Sequence[Sequence]) -> Dict[str, np.ndarray]:
    """Rows -> {column name: typed numpy array} (the internal execution
    format: every transform is a vectorized numpy op over these)."""
    n = schema.num_columns()
    for i, r in enumerate(rows):
        if len(r) != n:
            raise ValueError(f"record {i}: width {len(r)} != schema "
                             f"width {n} ({schema.names()})")
    out: Dict[str, np.ndarray] = {}
    for j, col in enumerate(schema.columns):
        vals = [r[j] for r in rows]
        if col.ctype == INTEGER or col.ctype == TIME:
            out[col.name] = np.asarray([int(v) for v in vals], np.int64)
        elif col.ctype == FLOAT:
            out[col.name] = np.asarray([float(v) for v in vals], np.float32)
        else:
            out[col.name] = np.asarray([str(v) for v in vals], object)
    return out


def to_rows(schema: Schema, cols: Dict[str, np.ndarray]) -> List[List]:
    names = schema.names()
    n_rows = len(cols[names[0]]) if names else 0
    return [[cols[nm][i] for nm in names] for i in range(n_rows)]
