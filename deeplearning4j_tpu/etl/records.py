"""Record readers: file formats -> record rows.

Reference parity: org.datavec.api.records.reader — CSVRecordReader,
LineRecordReader, CollectionRecordReader (datavec-api records/reader/impl)
and org.datavec.image.recordreader.ImageRecordReader (datavec-data-image,
NativeImageLoader): each yields one record (list of values) per source
row/file, label derived from the parent directory for images.

TPU-native notes: image decode goes through PIL into HWC float32 (the
layer API transposes to its internal layout); there is no JavaCPP/OpenCV
binding layer to mirror because numpy IS the interchange format.
"""
from __future__ import annotations

import csv
import io
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np


class RecordReader:
    """Iterable over records (reference: records/reader/RecordReader)."""

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        """Re-read from the start (file readers are re-iterable)."""

    def num_records(self) -> Optional[int]:
        return None


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: impl/collection/
    CollectionRecordReader.java)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter(self._records)

    def num_records(self):
        return len(self._records)


class CSVRecordReader(RecordReader):
    """CSV file/str reader (reference: impl/csv/CSVRecordReader.java —
    skipNumLines + delimiter; quoting per csv module)."""

    def __init__(self, path: Optional[str] = None, *, text: Optional[str] = None,
                 delimiter: str = ",", skip_num_lines: int = 0):
        if (path is None) == (text is None):
            raise ValueError("pass exactly one of path= or text=")
        self._path = path
        self._text = text
        self._delim = delimiter
        self._skip = skip_num_lines

    def _stream(self):
        if self._path is not None:
            return open(self._path, "r", newline="")
        return io.StringIO(self._text)

    def __iter__(self):
        with self._stream() as fh:
            r = csv.reader(fh, delimiter=self._delim)
            for i, row in enumerate(r):
                if i < self._skip or not row:
                    continue
                yield [c.strip() for c in row]

    def num_records(self):
        return sum(1 for _ in self)

    def as_matrix(self) -> np.ndarray:
        """All-numeric fast path: the whole file as a float32 (rows,
        cols) matrix, parsed by the native C++ kernel when available
        (datavec keeps this hot loop native too; see
        deeplearning4j_tpu/native). File-backed readers only."""
        if self._path is None:
            rows = [[float(c) for c in r] for r in self]
            return np.asarray(rows, np.float32).reshape(len(rows), -1)
        from deeplearning4j_tpu.native import read_csv_f32
        return read_csv_f32(self._path, delimiter=self._delim,
                            skip_num_lines=self._skip)


class LineRecordReader(RecordReader):
    """One record per line (reference: impl/LineRecordReader.java)."""

    def __init__(self, path: Optional[str] = None, *, text: Optional[str] = None):
        if (path is None) == (text is None):
            raise ValueError("pass exactly one of path= or text=")
        self._path = path
        self._text = text

    def __iter__(self):
        if self._path is not None:
            with open(self._path, "r") as fh:
                for line in fh:
                    yield [line.rstrip("\n")]
        else:
            for line in self._text.splitlines():
                yield [line]


_IMG_EXT = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".pgm", ".gif", ".npy")


class ImageRecordReader(RecordReader):
    """Image-directory reader (reference: org.datavec.image.recordreader.
    ImageRecordReader + ParentPathLabelGenerator): walks
    root/<label>/<image>, yields [HWC float32 image array, label string].
    Images resize to (height, width); grayscale when channels == 1.
    .npy files load directly (shape (H, W, C) or (H, W))."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 root: Optional[str] = None, transform=None,
                 seed: int = 0):
        self.height, self.width, self.channels = height, width, channels
        # augmentation pipeline applied per image at read time
        # (reference: ImageRecordReader(h, w, c, labelGen, imageTransform))
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._files: List[Tuple[str, str]] = []
        self.labels: List[str] = []
        if root is not None:
            self.initialize(root)

    def initialize(self, root: str) -> "ImageRecordReader":
        labels = sorted(d for d in os.listdir(root)
                        if os.path.isdir(os.path.join(root, d)))
        self.labels = labels
        self._files = []
        for lab in labels:
            d = os.path.join(root, lab)
            for f in sorted(os.listdir(d)):
                if f.lower().endswith(_IMG_EXT):
                    self._files.append((os.path.join(d, f), lab))
        if not self._files:
            raise ValueError(f"no images under {root!r} "
                             f"(expected root/<label>/<image>)")
        return self

    def _load(self, path: str) -> np.ndarray:
        if path.endswith(".npy"):
            arr = np.load(path).astype(np.float32)
            if arr.ndim == 2:
                arr = arr[:, :, None]
        else:
            from PIL import Image
            img = Image.open(path)
            img = img.convert("L" if self.channels == 1 else "RGB")
            img = img.resize((self.width, self.height))
            arr = np.asarray(img, np.float32)
            if arr.ndim == 2:
                arr = arr[:, :, None]
        if arr.shape[:2] != (self.height, self.width):
            raise ValueError(f"{path}: image {arr.shape[:2]} != "
                             f"({self.height}, {self.width})")
        if arr.shape[2] != self.channels:
            raise ValueError(f"{path}: {arr.shape[2]} channels, "
                             f"want {self.channels}")
        return arr

    def __iter__(self):
        # transforms may legally change the decode size (crop/resize),
        # but every record in one pass must agree — enforce here with the
        # transform named, not as a cryptic stack/graph error downstream
        out_shape = None
        for path, label in self._files:
            img = self._load(path)
            if self.transform is not None:
                img = np.asarray(
                    self.transform.transform(img, self._rng), np.float32)
                if img.ndim != 3:
                    raise ValueError(
                        f"transform {type(self.transform).__name__} "
                        f"returned rank-{img.ndim} output for {path}")
                if out_shape is None:
                    out_shape = img.shape
                elif img.shape != out_shape:
                    raise ValueError(
                        f"transform {type(self.transform).__name__} "
                        f"produced {img.shape} for {path} but "
                        f"{out_shape} earlier in the pass — randomized "
                        f"size-changing transforms must fix an output "
                        f"size (RandomCrop/Resize), not vary it")
            yield [img, label]

    def num_records(self):
        return len(self._files)
