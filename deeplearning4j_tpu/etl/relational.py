"""Relational ETL: joins and group-by reductions over columnar data.

Reference parity: org.datavec.api.transform.join.Join (Inner/LeftOuter/
RightOuter/FullOuter on key columns) and org.datavec.api.transform.reduce.
Reducer (group-by key columns + per-column ReduceOp: Sum/Mean/Min/Max/
Range/Count/CountUnique/Stdev/TakeFirst/TakeLast).

TPU-native redesign: both are vectorized — group identification via
``np.unique(return_inverse=True)`` over key tuples and reductions via
per-group ``np.bincount``/segment reductions over whole columns, instead
of the reference's row-at-a-time MapReduce-style executors. The output is
columnar and feeds TransformProcess / batch stacking directly.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.etl.schema import (
    CATEGORICAL, FLOAT, INTEGER, STRING, TIME, ColumnMeta, Schema)

INNER = "inner"
LEFT_OUTER = "left_outer"
RIGHT_OUTER = "right_outer"
FULL_OUTER = "full_outer"


def _key_ids(cols: Dict[str, np.ndarray], keys: Sequence[str]
             ) -> np.ndarray:
    """Rows -> hashable key tuples (as an object array for np.unique)."""
    n = len(next(iter(cols.values()))) if cols else 0
    out = np.empty(n, dtype=object)
    arrays = [cols[k] for k in keys]
    for i in range(n):
        out[i] = tuple(a[i] for a in arrays)
    return out


def _null_of(meta: ColumnMeta):
    if meta.ctype in (INTEGER, TIME):
        return 0
    if meta.ctype == FLOAT:
        return np.nan
    return ""


@dataclasses.dataclass
class Join:
    """(reference: transform/join/Join.java + Join.Builder)"""
    join_type: str
    key_columns: Sequence[str]
    left_schema: Schema
    right_schema: Schema

    def __post_init__(self):
        if self.join_type not in (INNER, LEFT_OUTER, RIGHT_OUTER,
                                  FULL_OUTER):
            raise ValueError(f"unknown join type {self.join_type!r}")
        for k in self.key_columns:
            self.left_schema.column(k)
            self.right_schema.column(k)
        overlap = (set(self.left_schema.names())
                   & set(self.right_schema.names())) - set(self.key_columns)
        if overlap:
            raise ValueError(
                f"non-key columns appear on both sides: {sorted(overlap)}")

    def _nullable_sides(self):
        return {INNER: (False, False), LEFT_OUTER: (False, True),
                RIGHT_OUTER: (True, False),
                FULL_OUTER: (True, True)}[self.join_type]

    def output_schema(self) -> Schema:
        """Key columns, then left value columns, then right value columns.
        Value columns on a side that can be unmatched (outer joins) have
        INTEGER/TIME promoted to FLOAT — int arrays cannot hold the NaN
        null marker, and execute() promotes them the same way."""
        keys = list(self.key_columns)
        left_null, right_null = self._nullable_sides()

        def side(schema, nullable):
            out = []
            for c in schema.columns:
                if c.name in keys:
                    continue
                if nullable and c.ctype in (INTEGER, TIME):
                    out.append(ColumnMeta(c.name, FLOAT))
                else:
                    out.append(c)
            return out

        cols = [self.left_schema.column(k) for k in keys]
        cols += side(self.left_schema, left_null)
        cols += side(self.right_schema, right_null)
        return Schema(cols)

    def execute(self, left: Dict[str, np.ndarray],
                right: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        keys = list(self.key_columns)
        lk, rk = _key_ids(left, keys), _key_ids(right, keys)
        rindex: Dict[tuple, List[int]] = {}
        for i, k in enumerate(rk):
            rindex.setdefault(k, []).append(i)
        li_out: List[int] = []          # row index into left, -1 = null
        ri_out: List[int] = []
        for i, k in enumerate(lk):
            rows = rindex.get(k)
            if rows:
                for j in rows:
                    li_out.append(i)
                    ri_out.append(j)
            elif self.join_type in (LEFT_OUTER, FULL_OUTER):
                li_out.append(i)
                ri_out.append(-1)
        if self.join_type in (RIGHT_OUTER, FULL_OUTER):
            lmatched = set(lk.tolist())
            for i, k in enumerate(rk):
                if k not in lmatched:
                    li_out.append(-1)
                    ri_out.append(i)
        li = np.asarray(li_out, np.int64)
        ri = np.asarray(ri_out, np.int64)

        out: Dict[str, np.ndarray] = {}
        left_null, right_null = self._nullable_sides()
        for k in keys:
            # result_type, not the left dtype: fixed-width string keys from
            # the right side must not be truncated to the left's width
            vals = np.empty(len(li),
                            dtype=np.result_type(left[k], right[k]))
            has_l = li >= 0
            vals[has_l] = left[k][li[has_l]]
            vals[~has_l] = right[k][ri[~has_l]]
            out[k] = vals
        for idx, schema, cols, nullable in (
                (li, self.left_schema, left, left_null),
                (ri, self.right_schema, right, right_null)):
            for meta in schema.columns:
                if meta.name in keys:
                    continue
                src = cols[meta.name]
                if nullable and src.dtype.kind in "iu":
                    # match output_schema: nullable int/time columns are
                    # float even when this execution has no unmatched rows
                    src = src.astype(np.float64)
                vals = np.empty(len(idx), dtype=src.dtype)
                has = idx >= 0
                vals[has] = src[idx[has]]
                if (~has).any():
                    if vals.dtype.kind == "f":
                        vals[~has] = np.nan
                    else:
                        vals[~has] = _null_of(meta)
                out[meta.name] = vals
        return out


# ---------------------------------------------------------------------------
_NUMERIC_OPS = ("sum", "mean", "min", "max", "range", "stdev")
_ANY_OPS = ("count", "count_unique", "first", "last")


class Reducer:
    """Group-by reduction (reference: transform/reduce/Reducer.java:1 —
    key columns + a ReduceOp per value column).

    Vectorized: one np.unique over key tuples assigns group ids, then each
    column reduces with segment ops (bincount for sum/count; sort-based
    first/last) — no per-row loop over values.
    """

    def __init__(self, schema: Schema, key_columns: Sequence[str],
                 ops: Dict[str, str]):
        self.schema = schema
        self.key_columns = list(key_columns)
        for k in self.key_columns:
            schema.column(k)
        self.ops = dict(ops)
        for name, op in self.ops.items():
            meta = schema.column(name)
            if op in _NUMERIC_OPS and meta.ctype not in (INTEGER, FLOAT,
                                                         TIME):
                raise ValueError(
                    f"op {op!r} needs a numeric column, {name!r} is "
                    f"{meta.ctype}")
            if op not in _NUMERIC_OPS + _ANY_OPS:
                raise ValueError(f"unknown reduce op {op!r}")

    def output_schema(self) -> Schema:
        cols = [self.schema.column(k) for k in self.key_columns]
        for name, op in self.ops.items():
            meta = self.schema.column(name)
            if op in ("count", "count_unique"):
                ctype = INTEGER
            elif op in ("first", "last"):
                ctype = meta.ctype
            elif op in ("sum", "min", "max", "range") and \
                    meta.ctype in (INTEGER, TIME):
                ctype = meta.ctype
            else:
                ctype = FLOAT
            cols.append(ColumnMeta(f"{op}({name})", ctype, meta.categories))
        return Schema(cols)

    def execute(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        keys = _key_ids(cols, self.key_columns)
        uniq, inverse = np.unique(keys, return_inverse=True)
        g = len(uniq)
        out: Dict[str, np.ndarray] = {}
        # First occurrence of each group, for key values + stable order.
        first_idx = np.full(g, -1, np.int64)
        for i in range(len(keys) - 1, -1, -1):
            first_idx[inverse[i]] = i
        order = np.argsort(first_idx, kind="stable")
        rank = np.empty(g, np.int64)
        rank[order] = np.arange(g)
        gid = rank[inverse]             # group id in first-appearance order
        first_idx = first_idx[order]
        for k in self.key_columns:
            out[k] = cols[k][first_idx]
        counts = np.bincount(gid, minlength=g)
        for name, op in self.ops.items():
            v = cols[name]
            col = f"{op}({name})"
            if op == "count":
                out[col] = counts.astype(np.int64)
            elif op == "count_unique":
                u = np.asarray([len(set(v[gid == j].tolist()))
                                for j in range(g)], np.int64)
                out[col] = u
            elif op == "first":
                out[col] = v[first_idx]
            elif op == "last":
                last_idx = np.full(g, -1, np.int64)
                for i in range(len(v)):
                    last_idx[gid[i]] = i
                out[col] = v[last_idx]
            else:
                vf = v.astype(np.float64)
                sums = np.bincount(gid, weights=vf, minlength=g)
                if op == "sum":
                    res = sums
                elif op == "mean":
                    res = sums / counts
                elif op == "stdev":
                    sq = np.bincount(gid, weights=vf * vf, minlength=g)
                    var = sq / counts - (sums / counts) ** 2
                    # sample stdev like the reference (n-1 denominator)
                    n1 = np.maximum(counts - 1, 1)
                    res = np.sqrt(np.maximum(var * counts / n1, 0.0))
                else:  # min / max / range via sort-free segment extremes
                    mins = np.full(g, np.inf)
                    maxs = np.full(g, -np.inf)
                    np.minimum.at(mins, gid, vf)
                    np.maximum.at(maxs, gid, vf)
                    res = {"min": mins, "max": maxs,
                           "range": maxs - mins}[op]
                meta = self.schema.column(name)
                if meta.ctype in (INTEGER, TIME) and op in (
                        "sum", "min", "max", "range"):
                    res = res.astype(np.int64)
                else:
                    res = res.astype(np.float32)
                out[col] = res
        return out

    class Builder:
        """(reference: Reducer.Builder — keyColumns + sumColumns/
        meanColumns/... fluent ops)"""

        def __init__(self, schema: Schema):
            self._schema = schema
            self._keys: List[str] = []
            self._ops: Dict[str, str] = {}

        def key_columns(self, *names: str):
            self._keys.extend(names); return self

        def _add(self, op, names):
            for n in names:
                self._ops[n] = op
            return self

        def sum_columns(self, *names): return self._add("sum", names)
        def mean_columns(self, *names): return self._add("mean", names)
        def min_columns(self, *names): return self._add("min", names)
        def max_columns(self, *names): return self._add("max", names)
        def range_columns(self, *names): return self._add("range", names)
        def stdev_columns(self, *names): return self._add("stdev", names)
        def count_columns(self, *names): return self._add("count", names)

        def count_unique_columns(self, *names):
            return self._add("count_unique", names)

        def take_first_columns(self, *names): return self._add("first", names)
        def take_last_columns(self, *names): return self._add("last", names)

        def build(self) -> "Reducer":
            return Reducer(self._schema, self._keys, self._ops)

    @staticmethod
    def builder(schema: Schema) -> "Reducer.Builder":
        return Reducer.Builder(schema)
