"""TransformProcess: schema-aware columnar ETL DSL.

Reference parity: org.datavec.api.transform.TransformProcess.java:1 —
an ordered list of schema-transforming steps built fluently, executed
over records; plus the analysis-driven normalizers
(transform/analysis/*, NormalizerStandardize-style).

TPU-native redesign: steps run VECTORIZED over whole numpy columns (one
pass per step over contiguous arrays) instead of the reference's
row-by-row Writable interpreter, and the output feeds device-stacked
batches directly. Each step declares its schema effect, so
``final_schema()`` is static — mirroring the reference's
TransformProcess.getFinalSchema().
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.etl.schema import (
    CATEGORICAL, FLOAT, INTEGER, TIME, ColumnMeta, Schema, columnar)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ColumnAnalysis:
    """Per-column stats (reference: transform/analysis/columns/*Analysis)."""
    count: int = 0
    min: float = float("inf")
    max: float = float("-inf")
    mean: float = 0.0
    std: float = 0.0
    categories: Optional[Dict[str, int]] = None   # value -> count


class DataAnalysis:
    """(reference: transform/analysis/DataAnalysis)"""

    def __init__(self, schema: Schema, by_column: Dict[str, ColumnAnalysis]):
        self.schema = schema
        self.by_column = by_column

    def column(self, name: str) -> ColumnAnalysis:
        return self.by_column[name]


def analyze(schema: Schema, reader) -> DataAnalysis:
    """One pass over the reader computing per-column stats (reference:
    AnalyzeLocal.analyze)."""
    rows = list(reader)
    cols = columnar(schema, rows)
    out: Dict[str, ColumnAnalysis] = {}
    for meta in schema.columns:
        a = ColumnAnalysis(count=len(rows))
        v = cols[meta.name]
        if meta.ctype in (INTEGER, FLOAT):
            vf = v.astype(np.float64)
            a.min, a.max = float(vf.min()), float(vf.max())
            a.mean, a.std = float(vf.mean()), float(vf.std())
        elif meta.ctype == CATEGORICAL:
            uniq, counts = np.unique(v.astype(str), return_counts=True)
            a.categories = dict(zip(uniq.tolist(), counts.tolist()))
        out[meta.name] = a
    return DataAnalysis(schema, out)


# ---------------------------------------------------------------------------
class _Step:
    #: True for steps whose ``apply`` may emit a different number of
    #: rows than it received (filters). Streaming consumers that key
    #: state on a stable global record-id space
    #: (``datapipe.StreamingDataPipeline``) reject such steps up front.
    changes_row_count = False

    def apply_schema(self, schema: Schema) -> Schema:
        raise NotImplementedError

    def apply(self, schema: Schema, cols: Dict[str, np.ndarray]
              ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


@dataclasses.dataclass
class _RemoveColumns(_Step):
    names: Sequence[str]

    def apply_schema(self, s):
        drop = set(self.names)
        return Schema([c for c in s.columns if c.name not in drop])

    def apply(self, s, cols):
        drop = set(self.names)
        return {k: v for k, v in cols.items() if k not in drop}


@dataclasses.dataclass
class _KeepColumns(_Step):
    names: Sequence[str]

    def apply_schema(self, s):
        keep = list(self.names)
        return Schema([s.column(n) for n in keep])

    def apply(self, s, cols):
        return {n: cols[n] for n in self.names}


@dataclasses.dataclass
class _RenameColumn(_Step):
    old: str
    new: str

    def apply_schema(self, s):
        return Schema([ColumnMeta(self.new, c.ctype, c.categories)
                       if c.name == self.old else c for c in s.columns])

    def apply(self, s, cols):
        return {self.new if k == self.old else k: v for k, v in cols.items()}


@dataclasses.dataclass
class _FilterRows(_Step):
    """Keep rows where predicate(cols) is True (vectorized bool mask)."""
    predicate: Callable[[Dict[str, np.ndarray]], np.ndarray]
    changes_row_count = True

    def apply_schema(self, s):
        return s

    def apply(self, s, cols):
        mask = np.asarray(self.predicate(cols), bool)
        return {k: v[mask] for k, v in cols.items()}


@dataclasses.dataclass
class _CategoricalToInteger(_Step):
    name: str

    def apply_schema(self, s):
        c = s.column(self.name)
        if c.ctype != CATEGORICAL or not c.categories:
            raise ValueError(f"{self.name!r} is not categorical with known "
                             f"categories")
        return Schema([ColumnMeta(self.name, INTEGER) if x.name == self.name
                       else x for x in s.columns])

    def apply(self, s, cols):
        cats = list(s.column(self.name).categories)
        table = {c: i for i, c in enumerate(cats)}
        v = cols[self.name]
        try:
            idx = np.asarray([table[str(x)] for x in v], np.int64)
        except KeyError as e:
            raise ValueError(f"value {e.args[0]!r} not in categories "
                             f"{cats} of column {self.name!r}") from None
        out = dict(cols)
        out[self.name] = idx
        return out


@dataclasses.dataclass
class _CategoricalToOneHot(_Step):
    name: str

    def apply_schema(self, s):
        c = s.column(self.name)
        if c.ctype != CATEGORICAL or not c.categories:
            raise ValueError(f"{self.name!r} is not categorical")
        cols = []
        for x in s.columns:
            if x.name == self.name:
                cols.extend(ColumnMeta(f"{self.name}[{cat}]", FLOAT)
                            for cat in c.categories)
            else:
                cols.append(x)
        return Schema(cols)

    def apply(self, s, cols):
        cats = list(s.column(self.name).categories)
        table = {c: i for i, c in enumerate(cats)}
        v = cols[self.name]
        idx = np.asarray([table[str(x)] for x in v], np.int64)
        oh = np.eye(len(cats), dtype=np.float32)[idx]
        out = {}
        for k, arr in cols.items():
            if k == self.name:
                for j, cat in enumerate(cats):
                    out[f"{self.name}[{cat}]"] = oh[:, j]
            else:
                out[k] = arr
        return out


@dataclasses.dataclass
class _Normalize(_Step):
    """minmax or standardize using a DataAnalysis (reference:
    transform/normalize/Normalize + analysis-driven scalers)."""
    name: str
    mode: str
    analysis: DataAnalysis

    def apply_schema(self, s):
        return Schema([ColumnMeta(self.name, FLOAT) if c.name == self.name
                       else c for c in s.columns])

    def apply(self, s, cols):
        a = self.analysis.column(self.name)
        v = cols[self.name].astype(np.float32)
        if self.mode == "minmax":
            rng = (a.max - a.min) or 1.0
            v = (v - a.min) / rng
        elif self.mode == "standardize":
            v = (v - a.mean) / (a.std or 1.0)
        else:
            raise ValueError(f"unknown normalize mode {self.mode!r}")
        out = dict(cols)
        out[self.name] = v
        return out


@dataclasses.dataclass
class _MapColumn(_Step):
    """Vectorized fn over one column (reference: the *MathOp transforms,
    generalized — fn is a numpy ufunc/lambda over the whole column)."""
    name: str
    fn: Callable[[np.ndarray], np.ndarray]
    new_type: Optional[str] = None

    def apply_schema(self, s):
        if self.new_type is None:
            return s
        return Schema([ColumnMeta(self.name, self.new_type)
                       if c.name == self.name else c for c in s.columns])

    def apply(self, s, cols):
        out = dict(cols)
        out[self.name] = np.asarray(self.fn(cols[self.name]))
        return out


# ---------------------------------------------------------------------------
class TransformProcess:
    """(reference: TransformProcess.java:1 + .Builder)"""

    def __init__(self, initial_schema: Schema, steps: Sequence[_Step]):
        self.initial_schema = initial_schema
        self.steps = list(steps)

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.apply_schema(s)
        return s

    def execute_columnar(self, rows) -> Dict[str, np.ndarray]:
        """rows (or a RecordReader) -> transformed columnar dict."""
        s = self.initial_schema
        cols = columnar(s, list(rows))
        for st in self.steps:
            cols = st.apply(s, cols)
            s = st.apply_schema(s)
        return cols

    def execute(self, rows) -> List[List]:
        from deeplearning4j_tpu.etl.schema import to_rows
        return to_rows(self.final_schema(), self.execute_columnar(rows))

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[_Step] = []

        def remove_columns(self, *names: str):
            self._steps.append(_RemoveColumns(names)); return self

        def keep_columns(self, *names: str):
            self._steps.append(_KeepColumns(names)); return self

        def rename_column(self, old: str, new: str):
            self._steps.append(_RenameColumn(old, new)); return self

        def filter_rows(self, predicate):
            """predicate({col: np.array}) -> bool mask of rows to KEEP."""
            self._steps.append(_FilterRows(predicate)); return self

        def categorical_to_integer(self, name: str):
            self._steps.append(_CategoricalToInteger(name)); return self

        def categorical_to_one_hot(self, name: str):
            self._steps.append(_CategoricalToOneHot(name)); return self

        def normalize(self, name: str, mode: str, analysis: DataAnalysis):
            self._steps.append(_Normalize(name, mode, analysis)); return self

        def map_column(self, name: str, fn, new_type: Optional[str] = None):
            self._steps.append(_MapColumn(name, fn, new_type)); return self

        def build(self) -> "TransformProcess":
            tp = TransformProcess(self._schema, self._steps)
            tp.final_schema()   # validate the chain eagerly
            return tp

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ColumnQuality:
    """Per-column quality counts (reference:
    transform/quality/columns/*Quality — countValid/countInvalid/
    countMissing/countTotal, plus NaN/Inf for numeric columns)."""
    count_total: int = 0
    count_valid: int = 0
    count_invalid: int = 0
    count_missing: int = 0
    count_nan: int = 0
    count_infinite: int = 0


class DataQualityAnalysis:
    """(reference: transform/quality/DataQualityAnalysis)"""

    def __init__(self, schema: Schema, by_column: Dict[str, ColumnQuality]):
        self.schema = schema
        self.by_column = by_column

    def column(self, name: str) -> ColumnQuality:
        return self.by_column[name]

    def report(self) -> str:
        lines = ["data quality analysis"]
        for name, q in self.by_column.items():
            lines.append(
                f"  {name}: total={q.count_total} valid={q.count_valid} "
                f"invalid={q.count_invalid} missing={q.count_missing}"
                + (f" nan={q.count_nan} inf={q.count_infinite}"
                   if q.count_nan or q.count_infinite else ""))
        return "\n".join(lines)


def analyze_quality(schema: Schema, reader) -> DataQualityAnalysis:
    """One pass over raw records counting per-column validity (reference:
    AnalyzeLocal.analyzeQuality). Runs BEFORE columnar() so malformed
    cells are countable rather than fatal; a cell is missing when empty/
    None, invalid when it cannot take the column's type, and NaN/Inf are
    tracked for numeric columns."""
    out = {c.name: ColumnQuality() for c in schema.columns}
    for row in reader:
        for ci, meta in enumerate(schema.columns):
            # short (ragged) rows: the absent trailing cells are exactly
            # the malformed input this pass exists to count — missing,
            # never silently skipped
            val = row[ci] if ci < len(row) else None
            q = out[meta.name]
            q.count_total += 1
            sval = "" if val is None else str(val).strip()
            if sval == "":
                q.count_missing += 1
                continue
            if meta.ctype in (INTEGER, TIME):
                try:
                    int(sval)
                    q.count_valid += 1
                except ValueError:
                    q.count_invalid += 1
            elif meta.ctype == FLOAT:
                try:
                    f = float(sval)
                except ValueError:
                    q.count_invalid += 1
                    continue
                if np.isnan(f):
                    q.count_nan += 1
                    q.count_invalid += 1
                elif np.isinf(f):
                    q.count_infinite += 1
                    q.count_invalid += 1
                else:
                    q.count_valid += 1
            elif meta.ctype == CATEGORICAL and meta.categories:
                if sval in meta.categories:
                    q.count_valid += 1
                else:
                    q.count_invalid += 1
            else:
                q.count_valid += 1
    return DataQualityAnalysis(schema, out)
