"""Mixture-of-Experts with expert parallelism (EP).

No reference analogue (the reference has no MoE); this is new TPU-native
capability following the Switch Transformer / GShard recipe the way a
TPU framework expresses it:

- **Static shapes everywhere**: routing uses capacity-based dispatch/
  combine einsums (token → (expert, slot) one-hots), so the compiled
  step has NO data-dependent shapes — overflow tokens are dropped by
  construction and their combine weights are zero.
- **Expert parallelism is sharding, not message passing**: expert-major
  tensors (E, C, d) and expert weights (E, d, f) carry a sharding
  constraint on the EXPERT_AXIS mesh axis; GSPMD inserts the all-to-alls
  that move token slots between devices. No hand-written collectives.
- The load-balancing auxiliary loss is the standard fraction·probability
  dot product (Switch eq. 4), returned for the caller to add to the
  task loss.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.lax import with_sharding_constraint
from jax.sharding import PartitionSpec as P

EXPERT_AXIS = "expert"


def switch_gating(x, gate_w, capacity: int):
    """Top-1 (Switch) routing with per-expert capacity.

    x: (N, d) tokens; gate_w: (d, E). Returns (dispatch (N, E, C) f32
    one-hots, combine (N, E, C) f32 weights, aux_loss scalar).
    """
    e = gate_w.shape[1]
    logits = jnp.matmul(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)               # (N, E)
    expert_idx = jnp.argmax(probs, axis=-1)               # (N,)
    expert_1h = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
    gate = jnp.sum(probs * expert_1h, axis=-1)            # (N,)

    # position of each token within its expert's queue (arrival order).
    # Accumulated in int32: a float32 cumsum loses exactness past 2^24
    # tokens per group, silently corrupting queue positions (and thus
    # capacity drops) at scale.
    expert_1h_i = expert_1h.astype(jnp.int32)
    pos_in_expert = jnp.cumsum(expert_1h_i, axis=0) - expert_1h_i
    pos = jnp.sum(pos_in_expert * expert_1h_i, axis=-1)   # (N,) int32
    keep = pos < capacity                                 # overflow drops
    slot_1h = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    dispatch = (expert_1h * keep[:, None])[:, :, None] * slot_1h[:, None, :]
    combine = dispatch * gate[:, None, None]

    # load-balancing aux loss (Switch Transformer eq. 4)
    frac_tokens = jnp.mean(expert_1h, axis=0)             # (E,)
    frac_probs = jnp.mean(probs, axis=0)                  # (E,)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w_in, w_out, b_in=None, b_out=None,
            capacity_factor: float = 1.25,
            activation: Callable = jax.nn.gelu,
            expert_sharded: bool = False, n_groups: int = 1):
    """Switch-routed expert FFN over flattened tokens.

    x: (N, d); gate_w: (d, E); w_in: (E, d, f); w_out: (E, f, d).
    Returns (y (N, d), aux_loss). With ``expert_sharded`` the
    expert-major intermediates and weights get a sharding constraint on
    EXPERT_AXIS (call under a Mesh; GSPMD does the token all-to-alls).

    ``n_groups``: GShard-style token grouping. The materialized dispatch
    tensor is (G, S, E, C) with S = N/G and C ≈ cf·S/E, i.e. TOTAL size
    G·S·E·C = cf·N²/G — memory falls linearly in G (per-group it is
    cf·N²/G²). At large N pick G so that cf·N²/G fits the budget (e.g.
    G = N/1024 caps it at cf·N·1024); G=1 recovers plain Switch
    routing. Routing, capacity, and overflow drops become per-group.
    """
    n, d = x.shape
    e = gate_w.shape[1]
    if n % n_groups:
        raise ValueError(f"tokens {n} not divisible by n_groups "
                         f"{n_groups}")
    s = n // n_groups
    capacity = max(int(capacity_factor * s / e), 1)

    def route(xg):
        return switch_gating(xg, gate_w, capacity)

    if n_groups == 1:
        dispatch, combine, aux = route(x)
        dispatch = dispatch[None]
        combine = combine[None]
        xg = x[None]
    else:
        xg = x.reshape(n_groups, s, d)
        dispatch, combine, aux = jax.vmap(route)(xg)
        aux = jnp.mean(aux)

    expert_inputs = jnp.einsum("gsec,gsd->gecd",
                               dispatch.astype(x.dtype), xg)
    if expert_sharded:
        spec = P(None, EXPERT_AXIS, None, None)
        expert_inputs = with_sharding_constraint(expert_inputs, spec)
        w_in = with_sharding_constraint(w_in, P(EXPERT_AXIS, None, None))
        w_out = with_sharding_constraint(w_out, P(EXPERT_AXIS, None, None))
    h = jnp.einsum("gecd,edf->gecf", expert_inputs, w_in.astype(x.dtype))
    if b_in is not None:
        h = h + b_in.astype(x.dtype)[None, :, None, :]
    h = activation(h)
    out = jnp.einsum("gecf,efd->gecd", h, w_out.astype(x.dtype))
    if b_out is not None:
        out = out + b_out.astype(x.dtype)[None, :, None, :]
    if expert_sharded:
        out = with_sharding_constraint(out, P(None, EXPERT_AXIS, None,
                                              None))
    y = jnp.einsum("gsec,gecd->gsd", combine.astype(x.dtype), out)
    return y.reshape(n, d), jnp.asarray(aux, jnp.float32)


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32):
    """Expert weight pytree: gate (d,E), w_in (E,d,f), w_out (E,f,d)."""
    k1, k2, k3 = (rng.normal(size=s).astype(dtype) for s in
                  ((d_model, n_experts), (n_experts, d_model, d_ff),
                   (n_experts, d_ff, d_model)))
    return {
        "gate_w": k1 * (1.0 / jnp.sqrt(d_model)).astype(dtype),
        "w_in": k2 * (1.0 / jnp.sqrt(d_model)).astype(dtype),
        "w_out": k3 * (1.0 / jnp.sqrt(d_ff)).astype(dtype),
    }


def expert_parallel_specs():
    """NamedSharding PartitionSpecs for the MoE param pytree: experts
    sharded over EXPERT_AXIS, gate replicated."""
    return {
        "gate_w": P(None, None),
        "w_in": P(EXPERT_AXIS, None, None),
        "w_out": P(EXPERT_AXIS, None, None),
    }


def moe_train_step(params, x, targets, lr: float = 1e-2,
                   aux_weight: float = 0.01, expert_sharded: bool = False):
    """One SGD step on an MoE regression head — the EP building block the
    multichip dryrun compiles over a ('data','expert') mesh."""
    def loss_fn(p):
        y, aux = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"],
                         expert_sharded=expert_sharded)
        return jnp.mean((y - targets) ** 2) + aux_weight * aux

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                        params, grads)
    return new_params, loss
