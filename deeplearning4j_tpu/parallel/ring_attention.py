"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Reference parity: NONE — the reference predates long-context training
(SURVEY.md §5: attention exists only as single-device fused ops,
libnd4j generic/nn/multi_head_dot_product_attention.cpp). This is a new
first-class capability, designed TPU-first:

- **Ring attention**: shard the sequence over the 'seq' mesh axis; each
  step computes one (q-block × kv-block) tile and rotates the kv shard to
  the next neighbor with lax.ppermute — a pure ICI-neighbor transfer that
  overlaps with the tile matmul — while a flash-style running
  (max, denom, accum) makes the softmax exact across blocks
  (Liu et al. 2023 blockwise formulation).
- **Ulysses attention**: all_to_all swaps the sequence shard for a head
  shard, runs full-sequence attention on head-local data, and swaps back
  — better when heads ≥ devices and ICI all-to-all is cheap (within a
  v5e slice it is).

Both are exact: outputs match single-device softmax attention to
numerical tolerance (tested on the CPU mesh).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:                           # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from deeplearning4j_tpu.parallel import collectives
from deeplearning4j_tpu.parallel.mesh import SEQ_AXIS, DeviceMesh


def _shard_map_norep(**kw):
    """shard_map with the replication check off, across jax versions
    (>= 0.8 spells it check_vma; older, check_rep)."""
    def deco(f):
        try:
            return _shard_map(f, check_vma=False, **kw)
        except TypeError:
            return _shard_map(f, check_rep=False, **kw)
    return deco


def _block_attn(q, k, v, m, l, o, scale, mask=None):
    """One blockwise-softmax accumulation step (flash-attention update).

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); m/l: (B, H, Tq); o like q.
    """
    # float32 accumulation regardless of input dtype (bf16 running sums
    # lose ~1e-2 relative accuracy over long sequences; standard flash
    # practice is f32 m/l/o with a cast at the end)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) → nan
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    # corr is (B, H, Tq); o is (B, Tq, H, D)
    o_new = o * jnp.moveaxis(corr, 1, 2)[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v,
                   preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def ring_attention(q, k, v, mesh: DeviceMesh, causal: bool = False,
                   axis_name: str = SEQ_AXIS):
    """Exact attention with the sequence sharded over ``axis_name``.

    q/k/v: (batch, seq, heads, head_dim), seq sharded over the mesh axis.
    Returns same-shaped output, seq-sharded.
    """
    n = mesh.axis_size(axis_name)
    scale = 1.0 / (q.shape[-1] ** 0.5)

    spec = P(None, axis_name, None, None)

    @_shard_map_norep(mesh=mesh.mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    def _ring(q_blk, k_blk, v_blk):
        b, tq, h, d = q_blk.shape
        tk = k_blk.shape[1]
        my = lax.axis_index(axis_name)
        q_pos = my * tq + jnp.arange(tq)                    # global q positions

        def step(i, carry):
            m, l, o, k_cur, v_cur = carry
            src = (my - i) % n                              # kv block owner
            mask = None
            if causal:
                k_pos = src * tk + jnp.arange(tk)
                mask = q_pos[:, None] >= k_pos[None, :]     # (Tq, Tk)
                mask = mask[None, None, :, :]               # (1,1,Tq,Tk)
            m, l, o = _block_attn(q_blk, k_cur, v_cur, m, l, o, scale, mask)
            k_nxt = collectives.ring_permute(k_cur, axis_name)
            v_nxt = collectives.ring_permute(v_cur, axis_name)
            return m, l, o, k_nxt, v_nxt

        m0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, tq), jnp.float32)
        o0 = jnp.zeros(q_blk.shape, jnp.float32)
        m, l, o, _, _ = lax.fori_loop(0, n, step, (m0, l0, o0, k_blk, v_blk))
        denom = jnp.moveaxis(l, 1, 2)[..., None]            # (B, Tq, H, 1)
        return (o / jnp.maximum(denom, 1e-30)).astype(q_blk.dtype)

    return _ring(q, k, v)


def ulysses_attention(q, k, v, mesh: DeviceMesh, causal: bool = False,
                      axis_name: str = SEQ_AXIS):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): swap the
    seq shard for a head shard, attend over the full sequence locally,
    swap back. Heads must divide the axis size."""
    n = mesh.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"heads ({h}) must be divisible by mesh axis ({n})")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, axis_name, None, None)

    @_shard_map_norep(mesh=mesh.mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    def _ulysses(q_blk, k_blk, v_blk):
        # (B, T/n, H, D) --a2a--> (B, T, H/n, D)
        def seq_to_head(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        def head_to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

        qf, kf, vf = seq_to_head(q_blk), seq_to_head(k_blk), seq_to_head(v_blk)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            t = qf.shape[1]
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        of = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                        preferred_element_type=jnp.float32)
        # cast BEFORE the return all_to_all so bf16 (not f32) rides the ICI
        return head_to_seq(of.astype(q_blk.dtype))

    return _ulysses(q, k, v)
