"""Device mesh abstraction.

Reference parity: the reference has NO multi-device training left in-tree
(SURVEY.md §2.5 — ParallelWrapper/parameter-server removed); its only
placement abstractions are AffinityManager thread→device binding and the
JITA per-device allocator. This module is their TPU-native replacement and
the root of all parallelism here: a named `jax.sharding.Mesh` over the
chip topology; data/tensor/pipeline/sequence parallelism are just axis
names, and XLA inserts the ICI/DCN collectives implied by shardings.

Axis convention (scaling-book style):
- "data"  : batch sharding (DP)
- "model" : weight sharding (TP)
- "pipe"  : pipeline stages (PP)
- "seq"   : sequence/context parallelism (SP, ring attention)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"


class DeviceMesh:
    """A named mesh over available devices.

    DeviceMesh.create(data=4, model=2) → 4x2 mesh; axis sizes of 1 are
    kept (harmless) so sharding rules can always reference all axes.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    @staticmethod
    def create(devices: Optional[Sequence] = None, **axis_sizes: int) -> "DeviceMesh":
        devices = list(devices if devices is not None else jax.devices())
        if not axis_sizes:
            axis_sizes = {DATA_AXIS: len(devices)}
        names = tuple(axis_sizes.keys())
        sizes = tuple(int(v) for v in axis_sizes.values())
        n = int(np.prod(sizes))
        if n != len(devices):
            raise ValueError(f"mesh {dict(axis_sizes)} needs {n} devices, "
                             f"have {len(devices)}")
        arr = np.array(devices[:n]).reshape(sizes)
        return DeviceMesh(Mesh(arr, names))

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def axis_size(self, name: str) -> int:
        return self.mesh.shape[name] if name in self.mesh.axis_names else 1

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding from a partition spec; None entries = replicated."""
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __enter__(self):
        return self.mesh.__enter__()

    def __exit__(self, *a):
        return self.mesh.__exit__(*a)

    def __repr__(self):
        return f"DeviceMesh({dict(self.mesh.shape)})"
