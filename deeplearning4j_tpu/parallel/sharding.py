"""Sharding strategies: param/batch partition rules over a DeviceMesh.

Reference parity: none to mirror — the reference's data-parallel training
was removed upstream and it never had tensor parallelism (SURVEY.md §2.5).
Design follows the GSPMD/scaling-book recipe: pick a mesh, annotate array
shardings, let XLA insert collectives.

A strategy maps parameter NAMES (regex rules, first match wins) to
PartitionSpecs, plus batch specs for inputs. `tensor_parallel_rules`
produces Megatron-style specs for the nn layer naming scheme:
column-parallel for even dense layers (shard n_out), row-parallel for the
following layer (shard n_in) — XLA places the psum where the row-parallel
matmul contracts over the sharded dim.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, DeviceMesh)


@dataclasses.dataclass
class ShardingRule:
    pattern: str                      # regex on parameter name
    spec: Tuple[Optional[str], ...]   # PartitionSpec entries

    def matches(self, name: str) -> bool:
        return re.search(self.pattern, name) is not None

    def to_json(self) -> dict:
        # PartitionSpec entries are None | axis name | tuple of axis
        # names; tuples serialize as lists and round-trip back below
        return {"pattern": self.pattern,
                "spec": [list(e) if isinstance(e, tuple) else e
                         for e in self.spec]}

    @staticmethod
    def from_json(d: dict) -> "ShardingRule":
        return ShardingRule(
            pattern=d["pattern"],
            spec=tuple(tuple(e) if isinstance(e, list) else e
                       for e in d["spec"]))


class ShardingStrategy:
    """Resolves shardings for params and batch over a mesh."""

    def __init__(self, mesh: DeviceMesh, param_rules: Sequence[ShardingRule] = (),
                 batch_axes: Tuple[Optional[str], ...] = (DATA_AXIS,)):
        self.mesh = mesh
        self.param_rules = list(param_rules)
        self.batch_axes = batch_axes

    def to_spec(self) -> "ShardingSpec":
        """The declarative, serializable form of this live strategy:
        axis sizes from the mesh, the explicit rule list (presets were
        already expanded into rules at build time), and the batch
        PartitionSpec — what ``TrainingConfig.to_json`` emits when
        ``tc.sharding`` holds a strategy rather than a spec.

        The batch (data) axis is emitted as ``-1`` ("fill with the
        remaining devices") rather than its current concrete size:
        a serialized config must rebind elastically when the relaunched
        job has fewer devices — freezing the data extent at save time
        would make ``build()`` fail on exactly the shrunken topology
        the sharding field exists to survive. Model/pipe axes keep
        their concrete sizes (they encode the layout of the rules)."""
        axes = {str(k): int(v) for k, v in self.mesh.mesh.shape.items()}
        fill = next((a for a in self.batch_axes
                     if isinstance(a, str) and a in axes), None)
        if fill is None and len(axes) == 1:
            fill = next(iter(axes))
        if fill is not None:
            axes[fill] = -1
        return ShardingSpec(
            axes=axes,
            preset="data_parallel",        # no preset rules to re-add
            rules=list(self.param_rules),
            batch_axes=tuple(self.batch_axes))

    def param_spec(self, name: str, ndim: int) -> PartitionSpec:
        for rule in self.param_rules:
            if rule.matches(name):
                spec = [a for a in rule.spec]
                # pad/trim to rank
                spec = (spec + [None] * ndim)[:ndim]
                return PartitionSpec(*spec)
        return PartitionSpec()  # replicated

    def param_sharding(self, name: str, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh.mesh, self.param_spec(name, ndim))

    def batch_sharding(self, ndim: int) -> NamedSharding:
        spec = (list(self.batch_axes) + [None] * ndim)[:ndim]
        return NamedSharding(self.mesh.mesh, PartitionSpec(*spec))

    def window_sharding(self, ndim: int) -> NamedSharding:
        """Sharding for a fused-window stacked batch (K, batch, ...):
        the leading steps axis replicates (every step runs on every
        chip), the batch axes shard as usual one dim further in — so
        windows stack under the existing NamedShardings."""
        spec = ([None] + list(self.batch_axes) + [None] * ndim)[:ndim]
        return NamedSharding(self.mesh.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh.mesh, PartitionSpec())


def data_parallel(mesh: DeviceMesh) -> ShardingStrategy:
    """Pure DP: batch over 'data', params replicated; XLA AllReduces grads
    (the TPU-native replacement for the reference's removed
    ParallelWrapper/GradientsAccumulator)."""
    return ShardingStrategy(mesh, param_rules=(), batch_axes=(DATA_AXIS,))


def tensor_parallel_rules() -> List[ShardingRule]:
    """Megatron-style rules for the nn layer naming scheme
    (layer{i}_dense_W etc.): alternate column/row parallel so activations
    stay sharded between the pair and one psum closes the block."""
    return [
        # dense/output kernels: shard the output dim (column parallel)
        ShardingRule(r"_dense_W$", (None, MODEL_AXIS)),
        ShardingRule(r"_out_W$", (None, MODEL_AXIS)),
        # biases follow their kernel's output dim
        ShardingRule(r"_dense_b$", (MODEL_AXIS,)),
        ShardingRule(r"_out_b$", (MODEL_AXIS,)),
        # conv kernels HWIO: shard output channels
        ShardingRule(r"_conv_W$", (None, None, None, MODEL_AXIS)),
        ShardingRule(r"_conv_b$", (MODEL_AXIS,)),
        # LSTM: shard the 4*units gate dim
        ShardingRule(r"_lstm_Wih$", (None, MODEL_AXIS)),
        ShardingRule(r"_lstm_Whh$", (None, MODEL_AXIS)),
        ShardingRule(r"_lstm_b$", (MODEL_AXIS,)),
        # embeddings: shard the vocab dim (row parallel lookup)
        ShardingRule(r"_embedding_W$", (MODEL_AXIS, None)),
    ]


def data_and_tensor_parallel(mesh: DeviceMesh) -> ShardingStrategy:
    """2D DP×TP: batch over 'data', weights over 'model'."""
    return ShardingStrategy(mesh, param_rules=tensor_parallel_rules(),
                            batch_axes=(DATA_AXIS,))


def megatron_tensor_parallel_rules(param_names,
                                   warn_empty: bool = True) -> List[ShardingRule]:
    """Megatron-style COLUMN→ROW alternation derived from the actual
    parameter names of a built network (scaling-book MLP recipe): the
    first dense kernel of each consecutive dense pair shards its OUTPUT
    dim (column parallel — activations leave sharded on 'model'), the
    second shards its INPUT dim (row parallel — XLA closes the pair with
    ONE psum where the contraction meets the sharded dim). Column-layer
    biases shard with their kernel; row-layer biases replicate (added
    after the psum).

    Fixes the column-only scheme (round-3 Weak #6): column-only forces an
    all-gather of every activation between layers; the alternation keeps
    activations sharded through the pair and halves TP communication.
    """
    dense = [n for n in param_names
             if re.match(r"^(.*?)(?:_dense|_out)_W$", n)]
    if not dense and warn_empty:
        import warnings
        warnings.warn(
            "megatron_tensor_parallel_rules: no dense/output kernels found "
            "in the parameter names — tensor parallelism will be OFF "
            "(custom vertex names need explicit ShardingRules)")
    rules: List[ShardingRule] = []
    for i, wname in enumerate(dense):
        stem = wname[:-1]                       # strip the trailing 'W'
        if i % 2 == 0:                          # column parallel
            rules.append(ShardingRule("^" + re.escape(wname) + "$",
                                      (None, MODEL_AXIS)))
            rules.append(ShardingRule("^" + re.escape(stem) + "b$",
                                      (MODEL_AXIS,)))
        else:                                   # row parallel
            rules.append(ShardingRule("^" + re.escape(wname) + "$",
                                      (MODEL_AXIS, None)))
            rules.append(ShardingRule("^" + re.escape(stem) + "b$",
                                      (None,)))
    # everything else follows the generic rules
    rules.extend(tensor_parallel_rules())
    return rules


def transformer_tensor_parallel_rules() -> List[ShardingRule]:
    """Megatron attention + MLP + embedding rules for the transformer
    naming schemes in this repo (zoo/gpt: ``h{i}/attn/qkv/kernel``...;
    nn attention layers: ``..._attn_Wq``...) — the full Megatron-LM
    layout (round-4 Weak #5: qkv/proj/embeddings fell through to
    replication):

    - qkv projection: COLUMN parallel (shard the fused 3H output dim —
      each model rank owns a head subset);
    - attention output projection: ROW parallel (shard the input dim;
      one psum closes the attention block);
    - MLP up/fc: COLUMN; MLP down/proj: ROW (one psum closes the MLP);
    - token embedding: row-parallel over the VOCAB dim (each rank owns
      a vocab shard; the gather's psum combines) — position embeddings
      replicate (small).
    """
    return [
        # zoo/gpt naming
        ShardingRule(r"attn/qkv/kernel$", (None, MODEL_AXIS)),
        ShardingRule(r"attn/qkv/bias$", (MODEL_AXIS,)),
        ShardingRule(r"attn/proj/kernel$", (MODEL_AXIS, None)),
        ShardingRule(r"attn/proj/bias$", (None,)),
        ShardingRule(r"mlp/fc/kernel$", (None, MODEL_AXIS)),
        ShardingRule(r"mlp/fc/bias$", (MODEL_AXIS,)),
        ShardingRule(r"mlp/proj/kernel$", (MODEL_AXIS, None)),
        ShardingRule(r"mlp/proj/bias$", (None,)),
        ShardingRule(r"^wte$", (MODEL_AXIS, None)),
        ShardingRule(r"^wpe$", (None,)),
        # nn attention layers (RecurrentAttentionLayer etc.: _Wq/_Wk/_Wv
        # column, _Wo row)
        ShardingRule(r"_attn_W[qkv]$", (None, MODEL_AXIS)),
        ShardingRule(r"_attn_Wo$", (MODEL_AXIS, None)),
        # BERT-import naming (query/key/value/attention-output denses)
        ShardingRule(r"attention/self/(query|key|value)/kernel$",
                     (None, MODEL_AXIS)),
        ShardingRule(r"attention/self/(query|key|value)/bias$",
                     (MODEL_AXIS,)),
        ShardingRule(r"attention/output/dense/kernel$", (MODEL_AXIS, None)),
        ShardingRule(r"attention/output/dense/bias$", (None,)),
        ShardingRule(r"intermediate/dense/kernel$", (None, MODEL_AXIS)),
        ShardingRule(r"intermediate/dense/bias$", (MODEL_AXIS,)),
        ShardingRule(r"(?<!attention)/output/dense/kernel$",
                     (MODEL_AXIS, None)),
        ShardingRule(r"word_embeddings$", (MODEL_AXIS, None)),
    ]


def megatron_data_and_tensor_parallel(mesh: DeviceMesh,
                                      model) -> ShardingStrategy:
    """DP×TP with the full Megatron layout: transformer attention/MLP/
    embedding rules first (name-scheme based), then column→row
    alternation derived from ``model``'s remaining dense parameters."""
    sd = getattr(model, "samediff", model)
    names = list(sd.trainable_params())
    rules = transformer_tensor_parallel_rules()
    covered = {n for n in names if any(r.matches(n) for r in rules)}
    remaining = [n for n in names if n not in covered]
    # the alternation pass warns when it finds no dense kernels — that
    # is spurious when the transformer rules already cover the model
    rules += megatron_tensor_parallel_rules(remaining,
                                            warn_empty=not covered)
    return ShardingStrategy(mesh, param_rules=rules,
                            batch_axes=(DATA_AXIS,))


# ---------------------------------------------------------------------------
# declarative strategy specs — the TrainingConfig-citizen form

#: preset name → rule factory taking (model_or_None). ``data_parallel``
#: keeps params replicated; the others produce TP rules over 'model'.
_SPEC_PRESETS = {
    "data_parallel": lambda model: [],
    "tensor_parallel": lambda model: tensor_parallel_rules(),
    "transformer": lambda model: transformer_tensor_parallel_rules(),
}


@dataclasses.dataclass
class ShardingSpec:
    """Declarative, serializable description of a ShardingStrategy —
    the form that lives on ``TrainingConfig.sharding`` and round-trips
    through config serde like every other training knob.

    A ``ShardingStrategy`` holds live objects (a ``jax.sharding.Mesh``
    over concrete devices); this spec holds only *intent* — axis sizes,
    a rule preset, explicit per-layer rules — and ``build()`` binds it
    to whatever devices the restoring process actually has. That split
    is what makes elastic resume possible: a checkpoint records the
    topology it was SAVED under, the spec rebuilds the strategy for the
    topology it is RESTORED under (checkpoint/reshard.py).

    - ``axes``: ordered ``{axis_name: size}``; ONE size may be ``-1``
      ("fill with the remaining devices"), so ``{"data": -1}`` is pure
      DP over however many chips exist and ``{"data": -1, "model": 2}``
      is DP×TP that survives the data axis shrinking after a host loss.
    - ``preset``: named rule set ("data_parallel" | "tensor_parallel" |
      "transformer" | "megatron" — megatron derives column→row
      alternation from the model's parameter names at build time).
    - ``rules``: explicit ShardingRules, matched FIRST (before the
      preset's), for per-layer overrides.
    - ``batch_axes``: PartitionSpec entries for input batches (leading
      dims); the fused-window form derives from it (window_sharding).
    """
    axes: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {DATA_AXIS: -1})
    preset: str = "data_parallel"
    rules: Sequence[ShardingRule] = ()
    batch_axes: Tuple[Optional[str], ...] = (DATA_AXIS,)

    def resolve_axes(self, n_devices: int) -> Dict[str, int]:
        """Concrete axis sizes for ``n_devices`` (the one ``-1`` fills
        with whatever the fixed axes leave)."""
        sizes = {str(k): int(v) for k, v in self.axes.items()}
        fills = [k for k, v in sizes.items() if v == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one -1 (fill) axis allowed, "
                             f"got {fills}")
        fixed = 1
        for k, v in sizes.items():
            if v != -1:
                if v <= 0:
                    raise ValueError(f"axis {k!r} size must be positive "
                                     f"or -1, got {v}")
                fixed *= v
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"fixed axes {sizes} need a multiple of {fixed} "
                    f"devices, have {n_devices}")
            sizes[fills[0]] = max(1, n_devices // fixed)
        return sizes

    def validate(self, params: Optional[Dict[str, Tuple[int, ...]]] = None,
                 device_count: Optional[int] = None) -> Dict[str, int]:
        """Pure build-time checks, raising the SAME errors ``build()``
        would — without constructing a mesh or touching a device.
        Shared by ``build()`` and the static analyzer's config pass
        (analyze/configpass.py), so a bad spec is one named diagnostic
        instead of a mid-fit crash.

        - axis grammar: at most one ``-1`` fill, positive sizes,
          a known ``preset``;
        - ``batch_axes``/rule entries reference declared axis names;
        - with ``device_count``: the fixed axes divide it
          (``resolve_axes``);
        - with ``params`` (``{name: shape}``): every rule-matched
          parameter dim is divisible by its CONCRETE axis size (the
          fill axis is checked only when ``device_count`` resolves it).

        Returns the resolved (or partially resolved, when
        ``device_count`` is None) axis sizes."""
        sizes = self.resolve_axes(device_count) if device_count \
            else {str(k): int(v) for k, v in self.axes.items()}
        if device_count and not any(v == -1 for v in sizes.values()):
            # no fill axis: resolve_axes never compares the fixed
            # product against the device count, but DeviceMesh.create
            # will — raise its error here, pre-mesh
            n = 1
            for v in sizes.values():
                n *= v
            if n > int(device_count):
                raise ValueError(f"mesh {sizes} needs {n} devices, "
                                 f"have {device_count}")
        if len([v for v in sizes.values() if v == -1]) > 1:
            raise ValueError(f"at most one -1 (fill) axis allowed, "
                             f"got {sizes}")
        for k, v in sizes.items():
            if v != -1 and v <= 0:
                raise ValueError(f"axis {k!r} size must be positive "
                                 f"or -1, got {v}")
        if self.preset not in _SPEC_PRESETS and self.preset != "megatron":
            raise ValueError(
                f"unknown sharding preset {self.preset!r}; expected one "
                f"of {sorted(_SPEC_PRESETS) + ['megatron']} (use rules= "
                f"for custom layouts)")

        def _entry_axes(entry):
            if entry is None:
                return ()
            return entry if isinstance(entry, tuple) else (entry,)

        for a in self.batch_axes:
            for ax in _entry_axes(a):
                if ax not in sizes:
                    raise ValueError(
                        f"batch axis {ax!r} is not a declared mesh "
                        f"axis {sorted(sizes)}")
        rules = list(self.rules)
        for rule in rules:
            for entry in rule.spec:
                for ax in _entry_axes(entry):
                    if ax not in sizes:
                        raise ValueError(
                            f"rule {rule.pattern!r} shards over "
                            f"{ax!r}, not a declared mesh axis "
                            f"{sorted(sizes)}")
        if params:
            if self.preset == "megatron":
                check_rules = rules + megatron_tensor_parallel_rules(
                    list(params), warn_empty=False)
            else:
                check_rules = rules + _SPEC_PRESETS[self.preset](None)
            for name, shape in params.items():
                rule = next((r for r in check_rules if r.matches(name)),
                            None)
                if rule is None:
                    continue
                spec = (list(rule.spec) + [None] * len(shape))[:len(shape)]
                for dim, entry in zip(shape, spec):
                    extent = 1
                    for ax in _entry_axes(entry):
                        if ax not in sizes:
                            # a preset rule can shard a matched param
                            # over an axis this spec never declared
                            # (e.g. "transformer" with data-only axes)
                            # — at build time that dies inside
                            # device_put; here it is a named error
                            raise ValueError(
                                f"parameter {name!r} matches rule "
                                f"{rule.pattern!r} sharding over "
                                f"{ax!r}, not a declared mesh axis "
                                f"{sorted(sizes)}")
                        v = sizes[ax]
                        # an unresolved -1 fill axis is unknown until
                        # device_count binds it — skip, don't multiply
                        extent *= v if v > 0 else 1
                    if extent > 1 and dim % extent != 0:
                        raise ValueError(
                            f"parameter {name!r} dim {dim} is not "
                            f"divisible by axis extent {extent} "
                            f"(rule {rule.pattern!r}, spec {rule.spec})")
        return sizes

    def build(self, model=None,
              devices: Optional[Sequence] = None) -> ShardingStrategy:
        """Bind this spec to concrete devices (default: all visible).
        ``model`` is consulted only by the "megatron" preset (its rule
        derivation reads the built network's parameter names).
        Grammar/divisibility errors come from :meth:`validate` first —
        the same errors the static analyzer reports pre-compile."""
        import jax
        devices = list(devices if devices is not None else jax.devices())
        self.validate(device_count=len(devices))
        sizes = self.resolve_axes(len(devices))
        n = 1
        for v in sizes.values():
            n *= v
        mesh = DeviceMesh.create(devices=devices[:n], **sizes)
        rules = list(self.rules)
        if self.preset == "megatron":
            if model is not None:
                strat = megatron_data_and_tensor_parallel(mesh, model)
                rules += strat.param_rules
            else:
                rules += tensor_parallel_rules()
        elif self.preset in _SPEC_PRESETS:
            rules += _SPEC_PRESETS[self.preset](model)
        else:
            raise ValueError(
                f"unknown sharding preset {self.preset!r}; expected one "
                f"of {sorted(_SPEC_PRESETS) + ['megatron']} (use rules= "
                f"for custom layouts)")
        return ShardingStrategy(mesh, param_rules=rules,
                                batch_axes=tuple(self.batch_axes))

    # -- serde (rides TrainingConfig.to_json/from_json) -----------------
    def to_json(self) -> dict:
        return {"axes": {str(k): int(v) for k, v in self.axes.items()},
                "preset": self.preset,
                "rules": [r.to_json() for r in self.rules],
                "batch_axes": list(self.batch_axes)}

    @staticmethod
    def from_json(d) -> "Optional[ShardingSpec]":
        if d is None:
            return None
        return ShardingSpec(
            axes={str(k): int(v)
                  for k, v in d.get("axes", {DATA_AXIS: -1}).items()},
            preset=d.get("preset", "data_parallel"),
            rules=[ShardingRule.from_json(r) for r in d.get("rules", [])],
            batch_axes=tuple(d.get("batch_axes", [DATA_AXIS])))
