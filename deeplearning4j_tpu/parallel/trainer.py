"""Parallel training and serving over a DeviceMesh.

Reference parity:
- Training: NEW capability (the reference's ParallelWrapper/
  GradientsAccumulator data-parallel training was removed upstream,
  SURVEY.md §2.5). TPU-native design: place params/batch with
  NamedShardings and jit the SAME whole-graph train step SameDiff already
  compiles — GSPMD propagates shardings and inserts AllReduce over ICI for
  gradients; there is no separate "gradient sharing" code path to write.
- Serving: ParallelInference (deeplearning4j-parallelwrapper
  ParallelInference.java:54) ran N model replicas on N GPUs with
  host-thread affinity + dynamic batching; here a batch sharded over the
  'data' axis runs on all chips inside one compiled computation.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.parallel.sharding import (
    ShardingSpec, ShardingStrategy, data_parallel)


def shard_model(model_or_sd, strategy: ShardingStrategy) -> None:
    """Commit a model's parameter/state/constant arrays to the
    strategy's mesh shardings (the placement half of ParallelTrainer,
    shared with the ``TrainingConfig.sharding`` fit path and the
    resharded-restore path in checkpoint/reshard.py)."""
    sd = getattr(model_or_sd, "samediff", model_or_sd)
    st = strategy
    for n, v in sd.trainable_params().items():
        sd._arrays[n] = jax.device_put(v, st.param_sharding(n, v.ndim))
    for n, v in sd.state_vars_map().items():
        sd._arrays[n] = jax.device_put(v, st.param_sharding(n, v.ndim))
    for n, v in sd.constants_map().items():
        sd._arrays[n] = jax.device_put(v, st.replicated())
    if sd._updater_state is not None:
        # updater state leaves mirror their parameter's sharding
        new_state = {}
        for pname, leaves in sd._updater_state.items():
            sh = st.param_sharding(pname, np.ndim(
                sd._arrays[pname]) if pname in sd._arrays else 0)
            new_state[pname] = tuple(jax.device_put(l, sh) for l in leaves) \
                if isinstance(leaves, tuple) else jax.device_put(leaves, sh)
        sd._updater_state = new_state


def resolve_strategy(sd, spec_or_strategy) -> ShardingStrategy:
    """A live ShardingStrategy from either a strategy (as-is) or a
    declarative ShardingSpec, cached on the SameDiff per (spec json,
    device count) so repeated fits reuse one mesh."""
    if isinstance(spec_or_strategy, ShardingStrategy):
        return spec_or_strategy
    spec: ShardingSpec = spec_or_strategy
    import json
    key = (json.dumps(spec.to_json(), sort_keys=True), len(jax.devices()))
    cache = sd.__dict__.setdefault("_sharding_strategies", {})
    strat = cache.get(key)
    if strat is None:
        strat = cache[key] = spec.build(model=sd)
    return strat


def ensure_sharded(sd, spec_or_strategy, dataset_iterator):
    """The ``TrainingConfig.sharding`` fit hook: place the model on the
    spec's mesh and wrap the input iterator so batches land pre-sharded.
    A no-op when the iterator is already a _ShardedIterator (e.g. the
    fit was routed through ParallelTrainer, whose explicit strategy
    wins over the config spec)."""
    if isinstance(dataset_iterator, _ShardedIterator):
        return dataset_iterator
    strategy = resolve_strategy(sd, spec_or_strategy)
    shard_model(sd, strategy)
    return _ShardedIterator(dataset_iterator, strategy)


class _ShardedIterator:
    """Wraps a dataset iterator, placing each batch with the strategy's
    batch sharding (host→HBM transfer lands pre-sharded; the analogue of
    the reference's AsyncDataSetIterator device feed)."""

    def __init__(self, it, strategy: ShardingStrategy):
        self._it = it
        self._strategy = strategy
        # expose stacked_batches ONLY when the wrapped source has it, so
        # the scanned/cached-window fast tiers (which route on a hasattr
        # probe) survive the wrap: stacked (steps, batch, ...) arrays
        # land with the steps axis replicated and batch axes sharded —
        # wrapping a device-cached source no longer demotes the fit to
        # the streaming tier
        if callable(getattr(it, "stacked_batches", None)):
            self.stacked_batches = self._stacked_batches

    def reset(self):
        if hasattr(self._it, "reset"):
            self._it.reset()

    def _place(self, a):
        a = np.asarray(a)
        return jax.device_put(a, self._strategy.batch_sharding(a.ndim))

    def _place_stacked(self, a):
        import jax.numpy as jnp
        a = jnp.asarray(a)
        return jax.device_put(a, self._strategy.window_sharding(a.ndim))

    def _stacked_batches(self):
        feats, labels = self._it.stacked_batches()
        return ([self._place_stacked(f) for f in feats],
                [self._place_stacked(l) for l in labels])

    def window_sharding(self, ndim: int):
        """Fused-window placement hook (autodiff/window.py probes for
        this): stacked (K, batch, ...) windows land with the steps axis
        replicated and the batch axes sharded as usual."""
        return self._strategy.window_sharding(ndim)

    def __iter__(self):
        for batch in self._it:
            if isinstance(batch, dict):
                yield {k: self._place(v) for k, v in batch.items()}
            elif hasattr(batch, "features") and hasattr(batch, "labels"):
                yield (self._place(batch.features), self._place(batch.labels))
            elif isinstance(batch, (tuple, list)) and len(batch) == 2:
                f, l = batch
                fs = [self._place(x) for x in (f if isinstance(f, (list, tuple)) else [f])]
                ls = [self._place(x) for x in (l if isinstance(l, (list, tuple)) else [l])]
                yield (fs if len(fs) > 1 else fs[0],
                       ls if len(ls) > 1 else ls[0])
            else:
                yield batch


class ParallelTrainer:
    """Trains a SameDiff (or MultiLayerNetwork) across a mesh.

    Params are committed to their strategy shardings; the already-compiled
    train step follows input shardings (GSPMD), so DP/TP need no new
    step code — collectives appear in the compiled computation.
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 mesh: Optional[DeviceMesh] = None,
                 stats_storage=None):
        # accept MultiLayerNetwork or SameDiff
        self.sd = getattr(model, "samediff", model)
        self.model = model
        if strategy is None:
            # a declarative TrainingConfig.sharding spec is the next
            # most specific intent; fall back to pure DP over the mesh
            spec = getattr(getattr(self.sd, "training_config", None),
                           "sharding", None)
            if spec is not None and mesh is None:
                strategy = resolve_strategy(self.sd, spec)
            else:
                strategy = data_parallel(mesh or DeviceMesh.create())
        self.strategy = strategy
        self.stats_storage = stats_storage
        #: info dict of the last restore that crossed a topology change
        #: (None when the last restore matched the manifest topology)
        self.last_reshard: Optional[dict] = None

    def shard_params(self) -> None:
        """Commit parameter/state arrays to their mesh shardings."""
        shard_model(self.sd, self.strategy)

    def fit(self, dataset_iterator, epochs: int = 1, listeners: Sequence = ()):
        """Listeners pass through to the underlying SameDiff fit — a
        checkpoint.CheckpointListener here checkpoints sharded training
        exactly like single-device training."""
        self.shard_params()
        return self.sd.fit(_ShardedIterator(dataset_iterator, self.strategy),
                           epochs=epochs, listeners=listeners)

    def restore_latest(self, manager, strict: bool = True,
                       strategy: Optional[ShardingStrategy] = None,
                       verified_only: bool = False):
        """Resume from a checkpoint.CheckpointManager: restore the newest
        committed step into the model (host arrays), then re-commit the
        arrays to their mesh shardings. Returns (step, TrainingState) or
        None when no committed checkpoint exists.

        ``strategy=`` reshards the restored state into a DIFFERENT
        sharding than the trainer was constructed with (elastic resume
        onto a changed mesh; the override becomes the trainer's
        strategy). When the checkpoint's recorded topology differs from
        the target mesh the re-placement is surfaced as a
        ``checkpoint.reshard`` span plus a ``{"type": "reshard"}``
        record, and ``self.last_reshard`` holds the summary.

        ``verified_only`` routes through the manager's fingerprint-
        verified walk (integrity/) while KEEPING the mesh re-commit
        below — the rollback-to-verified path for sharded models."""
        res = manager.restore_latest(model=self.model, strict=strict,
                                     verified_only=verified_only)
        self.last_reshard = None
        if res is not None:
            # adopt the override only once a restore actually landed —
            # swapping before a None/raising restore would leave the
            # trainer's strategy pointing at a mesh its params (still
            # placed under the old one) have never been committed to
            if strategy is not None:
                self.strategy = strategy
            step, state = res
            from_topo = (state.metadata or {}).get("topology") or {}
            to_axes = {str(k): int(v)
                       for k, v in self.strategy.mesh.mesh.shape.items()}
            # compare the SAVED mesh extent against the target mesh —
            # not the process-wide device_count, which stays at e.g. 8
            # while a sub-mesh trainer legitimately runs on 4 of them
            # (an unsharded save has mesh_axes None, which != any mesh)
            changed = bool(from_topo) and \
                from_topo.get("mesh_axes") != to_axes
            if changed:
                t0 = time.perf_counter()
                with _tracer.span("checkpoint.reshard", cat="checkpoint",
                                  step=int(step)):
                    self.shard_params()
                self.last_reshard = {
                    "step": int(step),
                    "arrays": len(state.arrays),
                    "bytes": int(state.nbytes()),
                    "seconds": round(time.perf_counter() - t0, 6),
                    "from_mesh": from_topo.get("mesh_axes"),
                    "to_mesh": to_axes,
                    "from_devices": from_topo.get("device_count"),
                    "to_devices": self.strategy.mesh.n_devices}
                if self.stats_storage is not None:
                    self.stats_storage.put({"type": "reshard",
                                            "t": time.time(),
                                            **self.last_reshard})
            else:
                self.shard_params()
        return res


class ParallelInference:
    """Mesh-wide batched inference (reference:
    parallelism/ParallelInference.java:54 — replica-per-device workers,
    BATCHED mode). One compiled computation with the batch sharded over
    'data' replaces worker threads + affinity + observable queues."""

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 mesh: Optional[DeviceMesh] = None):
        self.model = model
        self.sd = getattr(model, "_sd_infer", None) or getattr(
            model, "samediff", model)
        if strategy is None:
            strategy = data_parallel(mesh or DeviceMesh.create())
        self.strategy = strategy

    def _ensure_on_mesh(self):
        """Place arrays on the mesh ONLY if they are not already there —
        existing mesh shardings (e.g. tensor-parallel params) are kept, so
        a sharded-to-fit model is never forcibly replicated."""
        sd, st = self.sd, self.strategy
        mesh_devices = frozenset(self.strategy.mesh.mesh.devices.flat)
        for n, v in {**sd.trainable_params(), **sd.state_vars_map(),
                     **sd.constants_map()}.items():
            if frozenset(v.sharding.device_set) != mesh_devices:
                sd._arrays[n] = jax.device_put(v, st.replicated())

    def output(self, x, output_names: Optional[Sequence[str]] = None):
        if hasattr(self.model, "_sync_infer"):
            self.model._sync_infer()
        sd, st = self.sd, self.strategy
        self._ensure_on_mesh()
        x = np.asarray(x)
        x = jax.device_put(x, st.batch_sharding(x.ndim))
        if output_names:
            names = list(output_names)
        elif sd.has_variable("output"):
            names = ["output"]                 # MultiLayerNetwork contract
        else:
            # ComputationGraph: resolve declared outputs via its name map
            conf = getattr(self.model, "conf", None)
            name_map = getattr(self.model, "_map_infer", None) or \
                getattr(self.model, "_map_train", None)
            if conf is not None and name_map is not None:
                names = [name_map[o] for o in conf.outputs]
            else:
                names = ["output"]
        ph_name = "input" if sd.has_variable("input") else sd.placeholders()[0]
        res = sd.output({ph_name: x}, names)
        return res[names[0]] if len(names) == 1 else res


class BatchedParallelInference:
    """Dynamic-batching serving mode (reference: ParallelInference
    InferenceMode.BATCHED + observers/BatchedInferenceObservable.java —
    concurrent observe() calls coalesce into one model invocation).

    TPU-native design: requests enqueue from any thread; a single
    dispatcher thread drains the queue, concatenates up to
    ``max_batch_size`` rows (waiting at most ``max_wait_ms`` after the
    first request), runs ONE compiled forward over the mesh, and scatters
    row slices back to per-request futures. One XLA computation per
    coalesced batch replaces the reference's worker threads + device
    affinity."""

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 mesh: Optional[DeviceMesh] = None,
                 max_batch_size: int = 32, max_wait_ms: float = 5.0):
        import queue as _queue
        import threading
        self._inner = ParallelInference(model, strategy=strategy, mesh=mesh)
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self._q: "_queue.Queue" = _queue.Queue()
        self._closed = False
        self._lock = threading.Lock()     # submit/close atomicity
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()
        self.batches_dispatched = 0       # observability (reference:
        self.requests_served = 0          # observer counts)

    # -- client side ----------------------------------------------------
    def submit(self, x):
        """Enqueue one request (features (b, ...)); returns a Future whose
        result is the model output rows for exactly this request."""
        from concurrent.futures import Future
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("BatchedParallelInference is closed")
            self._q.put((np.asarray(x), fut))
        return fut

    def output(self, x):
        """Synchronous convenience (single request)."""
        return self.submit(x).result()

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._worker.join(timeout=5)
        # fail any request that raced past the sentinel rather than
        # leaving its Future unresolved forever
        import queue as _queue
        while True:
            try:
                item = self._q.get_nowait()
            except _queue.Empty:
                break
            if item is not None and not item[1].done():
                item[1].set_exception(
                    RuntimeError("BatchedParallelInference closed"))

    # -- dispatcher -----------------------------------------------------
    def _loop(self):
        import queue as _queue
        import time as _time
        while True:
            item = self._q.get()
            if item is None:
                return
            batch = [item]
            rows = item[0].shape[0]
            deadline = _time.monotonic() + self.max_wait_ms / 1000.0
            while rows < self.max_batch_size:
                timeout = deadline - _time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except _queue.Empty:
                    break
                if nxt is None:
                    self._q.put(None)     # propagate shutdown
                    break
                batch.append(nxt)
                rows += nxt[0].shape[0]
            try:
                X = np.concatenate([b[0] for b in batch], axis=0)
                # EVERY dispatch is exactly max_batch_size rows: requests
                # larger than the cap (or coalescing overshoot) are sliced
                # into max-sized dispatches, and the tail pads up — so the
                # serving hot path only ever sees ONE compiled shape
                # (per-row-count or multiple-of-max shapes would recompile)
                n_real = X.shape[0]
                m = self.max_batch_size
                outs = []
                for start in range(0, n_real, m):
                    sl = X[start:start + m]
                    if sl.shape[0] < m:
                        sl = np.concatenate(
                            [sl, np.repeat(sl[-1:], m - sl.shape[0], 0)], 0)
                    out = self._inner.output(sl)
                    out = out[0] if isinstance(out, list) else out
                    outs.append(np.asarray(out.data))
                    self.batches_dispatched += 1
                arr = np.concatenate(outs, axis=0)[:n_real]
                off = 0
                for feats, fut in batch:
                    n = feats.shape[0]
                    fut.set_result(arr[off:off + n])
                    off += n
                    self.requests_served += 1
            except Exception as e:       # pragma: no cover - error path
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
