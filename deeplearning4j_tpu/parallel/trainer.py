"""Parallel training and serving over a DeviceMesh.

Reference parity:
- Training: NEW capability (the reference's ParallelWrapper/
  GradientsAccumulator data-parallel training was removed upstream,
  SURVEY.md §2.5). TPU-native design: place params/batch with
  NamedShardings and jit the SAME whole-graph train step SameDiff already
  compiles — GSPMD propagates shardings and inserts AllReduce over ICI for
  gradients; there is no separate "gradient sharing" code path to write.
- Serving: ParallelInference (deeplearning4j-parallelwrapper
  ParallelInference.java:54) ran N model replicas on N GPUs with
  host-thread affinity + dynamic batching; here a batch sharded over the
  'data' axis runs on all chips inside one compiled computation.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.parallel.sharding import (
    ShardingStrategy, data_parallel)


class _ShardedIterator:
    """Wraps a dataset iterator, placing each batch with the strategy's
    batch sharding (host→HBM transfer lands pre-sharded; the analogue of
    the reference's AsyncDataSetIterator device feed)."""

    def __init__(self, it, strategy: ShardingStrategy):
        self._it = it
        self._strategy = strategy

    def reset(self):
        if hasattr(self._it, "reset"):
            self._it.reset()

    def _place(self, a):
        a = np.asarray(a)
        return jax.device_put(a, self._strategy.batch_sharding(a.ndim))

    def __iter__(self):
        for batch in self._it:
            if isinstance(batch, dict):
                yield {k: self._place(v) for k, v in batch.items()}
            elif hasattr(batch, "features") and hasattr(batch, "labels"):
                yield (self._place(batch.features), self._place(batch.labels))
            elif isinstance(batch, (tuple, list)) and len(batch) == 2:
                f, l = batch
                fs = [self._place(x) for x in (f if isinstance(f, (list, tuple)) else [f])]
                ls = [self._place(x) for x in (l if isinstance(l, (list, tuple)) else [l])]
                yield (fs if len(fs) > 1 else fs[0],
                       ls if len(ls) > 1 else ls[0])
            else:
                yield batch


class ParallelTrainer:
    """Trains a SameDiff (or MultiLayerNetwork) across a mesh.

    Params are committed to their strategy shardings; the already-compiled
    train step follows input shardings (GSPMD), so DP/TP need no new
    step code — collectives appear in the compiled computation.
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 mesh: Optional[DeviceMesh] = None):
        # accept MultiLayerNetwork or SameDiff
        self.sd = getattr(model, "samediff", model)
        self.model = model
        if strategy is None:
            strategy = data_parallel(mesh or DeviceMesh.create())
        self.strategy = strategy

    def shard_params(self) -> None:
        """Commit parameter/state arrays to their mesh shardings."""
        sd, st = self.sd, self.strategy
        for n, v in sd.trainable_params().items():
            sd._arrays[n] = jax.device_put(v, st.param_sharding(n, v.ndim))
        for n, v in sd.state_vars_map().items():
            sd._arrays[n] = jax.device_put(v, st.param_sharding(n, v.ndim))
        for n, v in sd.constants_map().items():
            sd._arrays[n] = jax.device_put(v, st.replicated())
        if sd._updater_state is not None:
            # updater state leaves mirror their parameter's sharding
            new_state = {}
            for pname, leaves in sd._updater_state.items():
                sh = st.param_sharding(pname, np.ndim(
                    sd._arrays[pname]) if pname in sd._arrays else 0)
                new_state[pname] = tuple(jax.device_put(l, sh) for l in leaves) \
                    if isinstance(leaves, tuple) else jax.device_put(leaves, sh)
            sd._updater_state = new_state

    def fit(self, dataset_iterator, epochs: int = 1, listeners: Sequence = ()):
        self.shard_params()
        return self.sd.fit(_ShardedIterator(dataset_iterator, self.strategy),
                           epochs=epochs, listeners=listeners)


class ParallelInference:
    """Mesh-wide batched inference (reference:
    parallelism/ParallelInference.java:54 — replica-per-device workers,
    BATCHED mode). One compiled computation with the batch sharded over
    'data' replaces worker threads + affinity + observable queues."""

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 mesh: Optional[DeviceMesh] = None):
        self.model = model
        self.sd = getattr(model, "_sd_infer", None) or getattr(
            model, "samediff", model)
        if strategy is None:
            strategy = data_parallel(mesh or DeviceMesh.create())
        self.strategy = strategy

    def _ensure_on_mesh(self):
        """Place arrays on the mesh ONLY if they are not already there —
        existing mesh shardings (e.g. tensor-parallel params) are kept, so
        a sharded-to-fit model is never forcibly replicated."""
        sd, st = self.sd, self.strategy
        mesh_devices = frozenset(self.strategy.mesh.mesh.devices.flat)
        for n, v in {**sd.trainable_params(), **sd.state_vars_map(),
                     **sd.constants_map()}.items():
            if frozenset(v.sharding.device_set) != mesh_devices:
                sd._arrays[n] = jax.device_put(v, st.replicated())

    def output(self, x, output_names: Optional[Sequence[str]] = None):
        if hasattr(self.model, "_sync_infer"):
            self.model._sync_infer()
        sd, st = self.sd, self.strategy
        self._ensure_on_mesh()
        x = np.asarray(x)
        x = jax.device_put(x, st.batch_sharding(x.ndim))
        names = list(output_names) if output_names else ["output"]
        ph_name = "input" if sd.has_variable("input") else sd.placeholders()[0]
        res = sd.output({ph_name: x}, names)
        return res[names[0]] if len(names) == 1 else res
