"""Multi-host runtime: jax.distributed wiring + elastic checkpoint restart.

Reference parity: the reference has NO multi-node runtime (SURVEY.md §5 —
Spark/parameter-server removed upstream); failure handling there is
checkpointing (ModelSerializer + CheckpointListener) and the
FailureTestingListener fault injector. This module is the TPU-native
replacement: one process per host, PJRT/XLA collectives over ICI/DCN
(jax.distributed), and elastic recovery = deterministic
restart-from-latest-checkpoint — the scaling-book model where a slice
failure kills the job and the scheduler relaunches it.

Single-process use is first-class: initialize() is a no-op without a
coordinator, and ElasticTrainer runs (and is tested) on one host.
"""
from __future__ import annotations

import glob
import os
import re
from typing import Callable, Optional, Sequence

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Join the multi-host job (reference: nothing to mirror — NEW).

    With no coordinator_address (and none in the JAX_COORDINATOR_ADDRESS /
    COORDINATOR_ADDRESS env), single-process mode: no-op. Otherwise wraps
    jax.distributed.initialize — afterwards jax.devices() spans all hosts
    and every jit/collective runs SPMD over DCN+ICI.
    """
    coordinator_address = coordinator_address or \
        os.environ.get("JAX_COORDINATOR_ADDRESS") or \
        os.environ.get("COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return
    kw = {}
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if local_device_ids is not None:
        kw["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """Process 0 owns host-side side effects (checkpoint writes, logging).
    Analogue of the reference's single-JVM assumption."""
    return jax.process_index() == 0


def host_shard_assignment(n_shards: int) -> list:
    """THIS process's data-shard indices under the canonical per-host
    partition (``datapipe.shard_assignment``: round-robin, disjoint and
    total across hosts). ``datapipe.StreamingDataPipeline`` applies it
    automatically; this is the hook for custom readers that want the
    same split — e.g. pairing hand-rolled loaders with per-process
    checkpoint shards (docs/data_pipeline.md)."""
    from deeplearning4j_tpu.datapipe.manifest import shard_assignment
    return shard_assignment(n_shards, jax.process_index(),
                            jax.process_count())


def sync_global_devices(tag: str = "barrier") -> None:
    """Cross-host barrier (no-op single-process)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


class HostFailureError(RuntimeError):
    """A peer host failed to reach a barrier within the liveness
    timeout (SURVEY §5 failure detection — the reference's analogue is
    Spark-era heartbeating; here liveness is defined as barrier
    progress, the scaling-book model where a dead host means the
    collective never completes)."""


def barrier_with_timeout(tag: str = "barrier", timeout: float = 60.0,
                         _sync_fn: Optional[Callable] = None) -> None:
    """Liveness-checked barrier: raises HostFailureError if the global
    sync does not complete within ``timeout`` seconds (a hung/dead peer
    otherwise blocks forever). Single-process: no-op.

    The barrier runs in a worker thread; on timeout the thread is
    abandoned (the runtime cannot cancel a blocked collective) and the
    caller should checkpoint-and-exit so the scheduler can relaunch the
    slice — the elastic recovery path (ElasticTrainer.run resumes).
    """
    import threading
    sync = _sync_fn if _sync_fn is not None else sync_global_devices
    if _sync_fn is None and jax.process_count() <= 1:
        return
    err = []
    done = threading.Event()

    def _run():
        try:
            sync(tag)
        except Exception as e:      # surface remote failures too
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    if not done.wait(timeout):
        raise HostFailureError(
            f"barrier {tag!r} did not complete within {timeout}s — a "
            f"peer process is unreachable; checkpoint and restart the "
            f"job (ElasticTrainer resumes from the latest checkpoint)")
    if err:
        raise HostFailureError(
            f"barrier {tag!r} failed: {err[0]}") from err[0]


class ElasticTrainer:
    """Checkpoint-based elastic training driver.

    Reference parity: CheckpointListener (keep-last-N zips) +
    EarlyStoppingTrainer's resume story, extended with the missing piece —
    deterministic RESUME: ``run()`` always starts from the latest
    checkpoint if one exists, so a killed/restarted job (slice failure,
    preemption) continues instead of restarting. The fault-injection test
    (tests/test_multihost.py) kills training mid-run and proves the
    restarted run converges to the same state as an uninterrupted one.
    """

    def __init__(self, sd, checkpoint_dir: str, every_n_epochs: int = 1,
                 keep_last: int = 3, barrier_timeout: float = 600.0):
        self.sd = sd
        self.dir = str(checkpoint_dir)
        self.every = max(1, int(every_n_epochs))
        self.keep = keep_last
        self.barrier_timeout = barrier_timeout
        os.makedirs(self.dir, exist_ok=True)

    # -- checkpoint bookkeeping ----------------------------------------
    def _path(self, epoch: int) -> str:
        return os.path.join(self.dir, f"elastic_epoch_{epoch}.zip")

    def latest(self):
        """(path, epoch) of the newest checkpoint, or (None, -1)."""
        best, best_e = None, -1
        for p in glob.glob(os.path.join(self.dir, "elastic_epoch_*.zip")):
            m = re.search(r"elastic_epoch_(\d+)\.zip$", p)
            if m and int(m.group(1)) > best_e:
                best, best_e = p, int(m.group(1))
        return best, best_e

    def _save(self, epoch: int) -> None:
        if not is_coordinator():
            return
        # sd.save is atomic (checkpoint/atomic.py): a preemption mid-save
        # cannot leave a torn zip that latest() would then restore. For
        # sharded/async/retained checkpoints use checkpoint.CheckpointManager.
        self.sd.save(self._path(epoch), include_updater_state=True)
        saved = sorted(
            glob.glob(os.path.join(self.dir, "elastic_epoch_*.zip")),
            key=lambda p: int(re.search(r"(\d+)\.zip$", p).group(1)))
        while len(saved) > self.keep:
            os.remove(saved.pop(0))

    # -- elastic run ----------------------------------------------------
    def run(self, dataset_iterator, epochs: int,
            fault_hook: Optional[Callable[[int], None]] = None,
            strict_restore: bool = True):
        """Train ``epochs`` total epochs, resuming from the latest
        checkpoint. fault_hook(epoch) (tests/fault injection — reference
        FailureTestingListener.java:19) runs after each epoch and may
        raise to simulate a crash.

        strict_restore: a checkpoint whose array names do not cover the
        live graph's parameters raises instead of silently training the
        uncovered parameters from their fresh init (a renamed layer must
        not resume from initialization without telling anyone)."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        path, done = self.latest()
        if path is not None:
            restored = SameDiff.load(path)
            if strict_restore:
                live = set(self.sd.trainable_params()) | \
                    set(self.sd.state_vars_map())
                have = set(restored._arrays)
                missing = sorted(live - have)
                if missing:
                    raise ValueError(
                        f"checkpoint {path} does not cover live "
                        f"parameters {missing[:5]}{'...' if len(missing) > 5 else ''} "
                        f"— the graph changed since the checkpoint "
                        f"(renamed/added layers). Pass "
                        f"strict_restore=False to resume the matching "
                        f"subset from the checkpoint and the rest from "
                        f"fresh init.")
            # adopt restored arrays/updater state into the live graph
            for n, arr in restored._arrays.items():
                if n in self.sd._arrays:
                    self.sd._arrays[n] = arr
            self.sd._updater_state = restored._updater_state
            if restored.training_config is not None and \
                    self.sd.training_config is not None:
                self.sd.training_config.iteration_count = \
                    restored.training_config.iteration_count
        start = done + 1
        losses = []
        for epoch in range(start, epochs):
            h = self.sd.fit(dataset_iterator, epochs=1)
            losses.append(h.final_loss())
            # liveness-checked epoch barrier: a dead peer surfaces as
            # HostFailureError instead of an indefinite hang
            barrier_with_timeout(f"epoch_{epoch}", self.barrier_timeout)
            if (epoch + 1) % self.every == 0 or epoch == epochs - 1:
                self._save(epoch)
            if fault_hook is not None:
                fault_hook(epoch)
        return losses
