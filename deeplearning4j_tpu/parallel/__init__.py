"""Device-mesh parallelism: DP/TP/SP over XLA collectives.

The reference's distributed training was removed upstream (SURVEY.md §2.5);
this package is the TPU-native replacement designed per the GSPMD recipe:
named mesh → sharding annotations → XLA inserts ICI/DCN collectives.
"""
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, DeviceMesh)
from deeplearning4j_tpu.parallel.sharding import (
    ShardingRule, ShardingSpec, ShardingStrategy, data_and_tensor_parallel,
    data_parallel, megatron_data_and_tensor_parallel,
    megatron_tensor_parallel_rules, tensor_parallel_rules,
    transformer_tensor_parallel_rules)
from deeplearning4j_tpu.parallel.trainer import (
    BatchedParallelInference, ParallelInference, ParallelTrainer,
    ensure_sharded, resolve_strategy, shard_model)
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_attention, ulysses_attention)
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_forward, pipeline_model_train_step, pipeline_train_step,
    place_stage_params, sequential_forward, split_microbatches)
from deeplearning4j_tpu.parallel.moe import (
    EXPERT_AXIS, expert_parallel_specs, init_moe_params, moe_ffn,
    moe_train_step, switch_gating)
from deeplearning4j_tpu.parallel import collectives, multihost

__all__ = [
    "DeviceMesh", "DATA_AXIS", "MODEL_AXIS", "PIPE_AXIS", "SEQ_AXIS",
    "ShardingRule", "ShardingSpec", "ShardingStrategy", "data_parallel",
    "ensure_sharded", "resolve_strategy", "shard_model",
    "data_and_tensor_parallel", "tensor_parallel_rules",
    "ParallelTrainer", "ParallelInference", "BatchedParallelInference",
    "megatron_data_and_tensor_parallel", "megatron_tensor_parallel_rules",
    "ring_attention",
    "ulysses_attention", "collectives", "multihost",
    "pipeline_forward", "pipeline_train_step", "pipeline_model_train_step",
    "place_stage_params", "sequential_forward", "split_microbatches",
    "transformer_tensor_parallel_rules",
    "EXPERT_AXIS", "moe_ffn", "switch_gating", "init_moe_params",
    "expert_parallel_specs", "moe_train_step",
]
