"""Named collective wrappers for use inside shard_map-ped functions.

Reference parity: the reference's removed Aeron parameter-server /
GradientsAccumulator gradient sharing (SURVEY.md §2.5) — replaced wholesale
by XLA collectives over ICI/DCN. These wrappers exist so framework code
reads in terms of the collective vocabulary (all_reduce / all_gather /
reduce_scatter / all_to_all / permute) rather than raw lax calls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce_sum(x, axis_name: str):
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def all_reduce_max(x, axis_name: str):
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ring_permute(x, axis_name: str, shift: int = 1):
    """Send to the next device on the ring (CollectivePermute over ICI)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.psum(1, axis_name)
