"""Pipeline parallelism: GPipe-style microbatching over the 'pipe' axis.

Reference parity: none to mirror — the reference never had pipeline
parallelism (SURVEY.md §2.5 PP row: "stage sharding over pod slices +
microbatch loop" is a new TPU-native capability).

TPU-native design (scaling-book recipe, not a port):
- The model is decomposed into S structurally-identical stages whose
  parameters carry a leading stage axis sharded over the mesh's 'pipe'
  axis — each device (column) holds exactly its stage's weights.
- One `shard_map` over 'pipe' runs the classic GPipe schedule INSIDE a
  single jitted computation: at tick t each stage processes its in-flight
  microbatch and `lax.ppermute` rotates activations to the next stage
  over ICI. M microbatches drain in M+S-1 ticks (the bubble).
- `ppermute` is differentiable, so `jax.grad` through the pipelined
  forward yields the reverse pipeline schedule automatically — no
  hand-written backward pass, unlike every CUDA pipeline runtime.
- Composes with DP/TP: the same step function jits over a
  (pipe, data, model) mesh; batch stays sharded on 'data', stage weights
  may additionally shard on 'model'.

The bubble fraction is (S-1)/(M+S-1); choose microbatches >> stages.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, DeviceMesh


def stage_sharding(mesh: DeviceMesh, ndim: int) -> NamedSharding:
    """Sharding for stage-stacked parameters: leading axis over 'pipe'."""
    spec = (PIPE_AXIS,) + (None,) * (ndim - 1)
    return NamedSharding(mesh.mesh, PartitionSpec(*spec))


def place_stage_params(mesh: DeviceMesh, stage_params):
    """Device-put a pytree of (S, ...) stage-stacked params so each pipe
    column holds its own stage's slice."""
    return jax.tree_util.tree_map(
        lambda p: jax.device_put(p, stage_sharding(mesh, jnp.ndim(p))),
        stage_params)


def pipeline_forward(stage_fn: Callable, mesh: DeviceMesh,
                     microbatch_spec: Optional[PartitionSpec] = None,
                     extra_specs: Tuple = (),
                     param_specs=None):
    """Build fn(stage_params, microbatches, *extra) -> outputs running the
    GPipe schedule over the mesh's 'pipe' axis.

    stage_fn(params_slice, x, *extra) -> y must keep y.shape == x.shape
    (classic homogeneous-stage pipelining, e.g. transformer blocks).
    microbatches: (M, mb, ...); output: (M, mb, ...) after all S stages.
    extra args are replicated (e.g. an attention mask).

    Composition: on a (pipe, data, ...) mesh the microbatch dim 1 shards
    over 'data' by default, so each pipe column runs data-parallel
    columns of the same stage; stage_fn may additionally use explicit
    'model'-axis collectives for in-stage tensor parallelism —
    ``param_specs`` (a pytree of PartitionSpecs matching stage_params,
    each leading with PIPE_AXIS) declares per-leaf Megatron shardings,
    and stage_fn closes row-parallel contractions with
    ``lax.psum(..., 'model')``.
    """
    S = mesh.axis_size(PIPE_AXIS)

    pspec = PartitionSpec(PIPE_AXIS)
    if microbatch_spec is None:
        microbatch_spec = (PartitionSpec(None, DATA_AXIS)
                           if DATA_AXIS in mesh.axis_names
                           else PartitionSpec())
    xspec = microbatch_spec

    def _pp(stage_params, microbatches, *extra):
        stage = lax.axis_index(PIPE_AXIS)
        M = microbatches.shape[0]
        total = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(t, carry):
            buf, outs = carry
            # stage 0 injects microbatch t (clamped; injected garbage past
            # M-1 never reaches the output window), others take the
            # rotated activation
            idx = jnp.clip(t, 0, M - 1)
            inj = lax.dynamic_index_in_dim(microbatches, idx, 0,
                                           keepdims=False)
            x = jnp.where(stage == 0, inj, buf)
            y = stage_fn(jax.tree_util.tree_map(lambda p: p[0],
                                                stage_params), x, *extra)
            # last stage banks its result for microbatch t-(S-1)
            oidx = jnp.clip(t - (S - 1), 0, M - 1)
            bank = jnp.logical_and(stage == S - 1, t >= S - 1)
            outs = lax.cond(
                bank,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y.astype(o.dtype), oidx, 0),
                lambda o: o, outs)
            buf = lax.ppermute(y, PIPE_AXIS, perm)
            return buf, outs

        buf = jnp.zeros_like(microbatches[0])
        outs = jnp.zeros_like(microbatches)
        buf, outs = lax.fori_loop(0, total, tick, (buf, outs),
                                  unroll=False)
        # results live on the last stage; share them with every column so
        # the loss is computable anywhere (psum of one-hot contribution)
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, PIPE_AXIS)

    try:
        from jax import shard_map
    except ImportError:                       # older jax
        from jax.experimental.shard_map import shard_map

    def fn(stage_params, microbatches, *extra):
        pspecs = (param_specs if param_specs is not None else
                  jax.tree_util.tree_map(lambda _: pspec, stage_params))
        kw = dict(mesh=mesh.mesh,
                  in_specs=(pspecs, xspec) + tuple(
                      extra_specs or (xspec,) * len(extra)),
                  out_specs=xspec)
        try:
            sm = shard_map(_pp, check_vma=False, **kw)   # jax >= 0.8
        except TypeError:
            sm = shard_map(_pp, check_rep=False, **kw)
        return sm(stage_params, microbatches, *extra)

    return fn


def _default_sgd(p, g):
    return jax.tree_util.tree_map(lambda a, b: a - 1e-2 * b, p, g)


def split_microbatches(x, n_micro: int):
    """(B, ...) -> (M, B/M, ...) microbatches."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible into {n_micro} microbatches")
    return x.reshape(n_micro, B // n_micro, *x.shape[1:])


def merge_microbatches(x):
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                        mesh: DeviceMesh, n_micro: int,
                        optimizer_update: Optional[Callable] = None):
    """One jitted GPipe training step.

    stage_fn(params_slice, x) -> y  (homogeneous stages)
    loss_fn(final_activations (B, ...), labels) -> scalar
    optimizer_update(params, grads) -> new params  (default: SGD 1e-2)

    Returns step(stage_params, head_params, x, labels) ->
    (new_stage_params, new_head_params, loss): gradient flows back through
    the pipeline (reverse schedule generated by AD), gradients for stage
    weights land sharded on their own pipe column.
    """
    fwd = pipeline_forward(stage_fn, mesh)
    if optimizer_update is None:
        optimizer_update = _default_sgd

    def loss_of(stage_params, head_params, x, labels):
        mb = split_microbatches(x, n_micro)
        y = merge_microbatches(fwd(stage_params, mb))
        return loss_fn(y, head_params, labels)

    @jax.jit
    def step(stage_params, head_params, x, labels):
        (loss), grads = jax.value_and_grad(loss_of, argnums=(0, 1))(
            stage_params, head_params, x, labels)
        gs, gh = grads
        return (optimizer_update(stage_params, gs),
                optimizer_update(head_params, gh), loss)

    return step


def pipeline_model_train_step(embed_fn: Callable, stage_fn: Callable,
                              head_loss_fn: Callable, mesh: DeviceMesh,
                              n_micro: int,
                              optimizer_update: Optional[Callable] = None,
                              stage_param_specs=None):
    """One jitted train step for the NON-homogeneous model shape
    embed → homogeneous trunk → head (round-4 Weak #8: only same-shape
    trunks could be pipelined).

    TPU-native composition: the trunk — the only part with S
    structurally-identical stages — runs the GPipe schedule over the
    'pipe' axis; ``embed_fn`` (token/position lookup, arbitrary input
    shape → trunk shape) and ``head_loss_fn`` (trunk shape → scalar
    loss, e.g. final LN + tied-vocab logits + CE) run as ordinary SPMD
    computations around it in the SAME jit, sharded over 'data' (and
    'model' where their params carry TP specs). Their FLOPs are tiny
    next to the trunk's, so pinning them to pipe ranks (the GPU
    runtimes' approach) would only add bubble.

    embed_fn(embed_params, *inputs) -> (B, ...) trunk input
    stage_fn(stage_params_slice, h) -> h       (homogeneous trunk)
    head_loss_fn(head_params, h, *labels) -> scalar loss
    Returns step((embed_p, stage_p, head_p), inputs_tuple, labels_tuple)
    -> (new_params_triple, loss).
    """
    fwd = pipeline_forward(stage_fn, mesh, param_specs=stage_param_specs)
    if optimizer_update is None:
        optimizer_update = _default_sgd

    def loss_of(params, inputs, labels):
        embed_p, stage_p, head_p = params
        h = embed_fn(embed_p, *inputs)
        mb = split_microbatches(h, n_micro)
        y = merge_microbatches(fwd(stage_p, mb))
        return head_loss_fn(head_p, y, *labels)

    @jax.jit
    def step(params, inputs, labels):
        loss, grads = jax.value_and_grad(loss_of)(params, inputs, labels)
        new = tuple(optimizer_update(p, g) for p, g in zip(params, grads))
        return new, loss

    return step


def sequential_forward(stage_fn: Callable, stage_params, x, *extra):
    """Reference semantics: run the S stages back-to-back on one device —
    the numerics-equality baseline for the pipelined schedule."""
    S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    y = x
    for s in range(S):
        p = jax.tree_util.tree_map(lambda a: a[s], stage_params)
        y = stage_fn(p, y, *extra)
    return y
