"""integrity/ — detect→diagnose→recover for failures that DON'T raise.

The faults/ rail (divergence sentinel, rollback-and-retry), serving
resilience and the datapipe plane all key on exceptions; at fleet scale
the dominant remaining class raises nothing: wedged dispatches and
collectives that hang forever, silent data corruption that flips a
param bit without tripping the isfinite sentinel, and checkpoint
bit-rot discovered mid-rollback. This package closes that gap,
composed WITH the existing substrate rather than beside it:

- ``watchdog``    — :class:`StallWatchdog`: a daemon heartbeat thread
  arming an adaptive deadline (k × rolling-p50, compile grace) around
  every blocking device boundary the tracer already names (window
  dispatch, flush device_get, serving exec, checkpoint capture); on
  expiry it dumps all-thread stacks + the active memory plan + an HBM
  snapshot into a typed ``TrainingStalledError``, publishes
  ``{"type": "faults", "event": "stall"}`` and flips ``/healthz`` to
  503. A recoverable stall is retryable under ``FaultTolerantFit``'s
  normal rollback budget.
- ``fingerprint`` — device-side bitwise fingerprints of params +
  optimizer state (a uint32 word-sum emitted by the compiled window
  exactly like the PR-4 sentinel carry — one extra int per window),
  checked at flush boundaries: device-vs-host at checkpoint capture,
  fingerprint-stamped checkpoints re-verified at restore, a periodic
  replay probe (re-dispatch from a stashed carry, compare digests),
  and cross-replica agreement under DP sharding. Mismatch raises
  ``SilentCorruptionError``; ``FaultTolerantFit`` answers by rolling
  back to the last fingerprint-VERIFIED checkpoint.
- the checkpoint scrubber lives with its subsystem
  (``checkpoint.Scrubber``): rate-limited background re-hashing of
  committed step dirs against their manifests, quarantining rotten
  steps aside so ``restore_latest`` never lands on bit-rot mid-
  recovery. ``python -m deeplearning4j_tpu.checkpoint scrub <dir>``
  is the offline fleet-side CLI.

Arm it: ``TrainingConfig.fingerprints = True`` (+
``fingerprint_replay_every`` / ``fingerprint_replica_every``), a
``StallWatchdog(...).install()`` (or context manager) around the run,
and a ``checkpoint.Scrubber(manager)`` next to long-retention trees.
Clean-path training with the whole rail armed is bit-identical to
rail-off (tested; bench.py ``integrity_overhead``, ≤2% bar). See
docs/fault_tolerance.md "Non-raising failures".
"""
from deeplearning4j_tpu.checkpoint.scrub import Scrubber
from deeplearning4j_tpu.faults.errors import (SilentCorruptionError,
                                              TrainingStalledError)
from deeplearning4j_tpu.integrity.fingerprint import (
    check_probes, check_replica_agreement, make_fingerprint_fn,
    np_fingerprint, np_leaf_fingerprint, replica_fingerprints,
    state_fingerprint, tree_fingerprint, verify_state_stamp)
from deeplearning4j_tpu.integrity.watchdog import (StallWatchdog,
                                                   dump_all_stacks, guard)

__all__ = ["Scrubber", "SilentCorruptionError", "StallWatchdog",
           "TrainingStalledError", "check_probes",
           "check_replica_agreement", "dump_all_stacks", "guard",
           "make_fingerprint_fn", "np_fingerprint",
           "np_leaf_fingerprint", "replica_fingerprints",
           "state_fingerprint", "tree_fingerprint",
           "verify_state_stamp"]
