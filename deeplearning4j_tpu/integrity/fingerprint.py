"""Bitwise state fingerprints: silent-corruption detection for ~free.

The divergence sentinel (faults/sentinels.py) catches values that go
NON-FINITE; silent data corruption flips a bit and stays finite. The
fingerprint rail closes that gap with one deterministic 32-bit digest
of the full training state (params + state vars + optimizer state):

    fingerprint = sum mod 2^32 of every 32-bit word of every leaf

Why this exact construction (and not a "real" hash):

- **order-independent** — modular addition commutes, so the device
  (whatever reduce order XLA schedules) and the host (numpy, any leaf
  order) compute the SAME digest from the same bytes. That is what
  makes device-vs-host and device-vs-stamp comparisons meaningful.
- **single-bit-flip-complete** — flipping bit ``b`` of any word changes
  the sum by ±2^b mod 2^32 ≠ 0: every single-event upset is detected.
  (Coordinated multi-bit damage can cancel; that failure mode belongs
  to the sha256 manifest on disk, not to an in-dispatch digest.)
- **fuses into the step** — on device it is one memory-bound uint32
  reduce appended to the compiled window, emitted as ONE extra scalar
  output per window exactly like the PR-4 sentinel carry; the host
  reads it only at the flush boundaries it already syncs on.

Checks built on it (docs/fault_tolerance.md "Non-raising failures"):

- **capture check** — ``checkpoint.state.capture_training_state``
  recomputes the digest from the captured HOST bytes and compares it
  to the device digest of the same boundary: a corrupted device→host
  copy raises :class:`~deeplearning4j_tpu.faults.errors.
  SilentCorruptionError` before the damage can be committed.
- **fingerprint-stamped checkpoints** — the host digest rides
  ``TrainingState.metadata["integrity"]``; restore recomputes and
  verifies it (:func:`verify_state_stamp`), so a checkpoint that rots
  in a way the sha256 manifest can no longer witness (manifest and
  payload both rewritten) still fails typed.
- **replay probe** — the windowed fit re-dispatches a window from a
  stashed carry every ``TrainingConfig.fingerprint_replay_every``
  windows and compares the two digests: genuine in-dispatch SDC or
  nondeterminism makes them disagree (autodiff/window.py).
- **cross-replica agreement** — under DP sharding every replica holds
  the same params; :func:`check_replica_agreement` compares per-shard
  digests bitwise and names the diverged device.

With no fault present the rail never touches parameter math:
fingerprints-on training is bit-identical to off (tested).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

ALGO = "u32sum-v1"

_MASK = np.uint64(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# host (numpy) digest — must agree bit-for-bit with the device digest

def np_leaf_fingerprint(a) -> int:
    """Sum mod 2^32 of the 32-bit words of one array's raw bytes,
    mirroring the device construction per itemsize (8/16-bit elements
    zero-extend to uint32 EACH; 64-bit elements split into two words)."""
    a = np.ascontiguousarray(np.asarray(a))
    if a.size == 0:
        return 0
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    itemsize = a.dtype.itemsize
    if itemsize == 1:
        words = a.reshape(-1).view(np.uint8)
    elif itemsize == 2:
        words = a.reshape(-1).view(np.uint16)
    elif itemsize == 4:
        words = a.reshape(-1).view(np.uint32)
    elif itemsize == 8:
        words = a.reshape(-1).view(np.uint32)   # little-endian word pairs
    else:
        raise TypeError(f"unsupported itemsize {itemsize} "
                        f"(dtype {a.dtype})")
    # uint64 accumulate then fold: portable regardless of numpy's
    # overflow behavior on platform-sized sums
    return int(np.sum(words.astype(np.uint64)) & _MASK)


def np_fingerprint(leaves: Iterable) -> int:
    """Combined digest of many arrays (order-independent by
    construction — modular addition commutes)."""
    total = 0
    for leaf in leaves:
        total = (total + np_leaf_fingerprint(leaf)) & 0xFFFFFFFF
    return int(total)


def state_fingerprint(state) -> int:
    """Host digest of a ``checkpoint.TrainingState``: the same leaf set
    the device digest covers — arrays (trainable params + state vars)
    plus the optimizer-state leaves. Counters/normalizer stay outside
    (they are host-side ints the manifest already covers)."""
    leaves = list(state.arrays.values())
    if state.updater_leaves is not None:
        leaves.extend(state.updater_leaves)
    return np_fingerprint(leaves)


def verify_state_stamp(state, where: str = "restore") -> Optional[bool]:
    """Re-verify a fingerprint-stamped ``TrainingState``. Returns None
    when unstamped (pre-integrity checkpoints restore as before), True
    when the stamp matches, and raises
    :class:`~deeplearning4j_tpu.faults.errors.SilentCorruptionError`
    on a mismatch — the typed signal ``FaultTolerantFit`` answers by
    rolling back to the last *verified* checkpoint."""
    stamp = (state.metadata or {}).get("integrity")
    if not stamp or stamp.get("fingerprint") is None:
        return None
    expected = int(stamp["fingerprint"])
    actual = state_fingerprint(state)
    if actual != expected:
        from deeplearning4j_tpu.faults.errors import SilentCorruptionError
        raise SilentCorruptionError(
            f"checkpoint fingerprint stamp mismatch at {where}: state "
            f"hashes to {actual:#010x} but was stamped {expected:#010x} "
            f"(step {state.iteration}) — the payload changed since "
            f"capture in a way the sha256 manifest did not witness",
            check=f"stamp_{where}", expected=expected, actual=actual,
            step=int(state.iteration), epoch=int(state.epoch))
    return True


# ---------------------------------------------------------------------------
# device (traced) digest

def jnp_leaf_fingerprint(x):
    """Traced uint32 digest of one array — the device mirror of
    :func:`np_leaf_fingerprint` (bitcast to same-width unsigned words,
    zero-extend sub-32-bit words, split 64-bit words, wraparound sum)."""
    import jax
    import jax.numpy as jnp
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    nbits = jnp.dtype(x.dtype).itemsize * 8
    target = {8: jnp.uint8, 16: jnp.uint16,
              32: jnp.uint32, 64: jnp.uint32}[nbits]
    words = jax.lax.bitcast_convert_type(x, target)
    return jnp.sum(words.astype(jnp.uint32), dtype=jnp.uint32)


def tree_fingerprint(*trees):
    """Traced combined digest over pytrees (params, svars, optimizer
    state). Emitted by the compiled window as ONE extra uint32 scalar;
    order-independent, so it agrees with the host digest of the same
    leaves regardless of flattening order."""
    import jax
    import jax.numpy as jnp
    total = jnp.asarray(0, jnp.uint32)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            total = total + jnp_leaf_fingerprint(leaf)
    return total


def make_fingerprint_fn(sd):
    """A tiny jitted ``(params, svars, state) -> uint32`` digest
    program for tiers that do not thread the digest through the
    compiled step (the per-step fit dispatches it at flush boundaries).
    Cached on the graph's version-keyed fn cache."""
    import jax
    key = ("fingerprint_fn", sd._version)
    fn = sd._fn_cache.get(key)
    if fn is None:
        fn = jax.jit(tree_fingerprint)
        sd._fn_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# cross-replica agreement (DP sharding)

def replica_fingerprints(tree) -> Dict[str, List[Tuple[str, Tuple, int]]]:
    """Per-addressable-shard host digests of every array in ``tree``:
    ``{name: [(device, index_key, fingerprint), ...]}``. Shards that
    cover the SAME global slice (``index_key``) are replicas and must
    match bitwise."""
    out: Dict[str, List[Tuple[str, Tuple, int]]] = {}
    for name, arr in tree.items():
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            continue
        rows = []
        for sh in shards:
            key = tuple((s.start, s.stop, s.step)
                        for s in (sh.index if isinstance(sh.index, tuple)
                                  else (sh.index,)))
            rows.append((str(sh.device), key,
                         np_leaf_fingerprint(np.asarray(sh.data))))
        out[name] = rows
    return out


def check_replica_agreement(tree, raise_: bool = True) -> List[dict]:
    """Compare replicas bitwise: any two shards of the same array
    covering the same global slice must hold identical bytes. Returns
    the disagreement list (empty = agreement); with ``raise_`` (the
    default) a non-empty list raises
    :class:`~deeplearning4j_tpu.faults.errors.SilentCorruptionError`
    naming the array and devices — SDC on one replica, or
    nondeterministic collective math, depending on which side you
    trust."""
    bad: List[dict] = []
    for name, rows in replica_fingerprints(tree).items():
        groups: Dict[Tuple, List[Tuple[str, int]]] = {}
        for device, key, fp in rows:
            groups.setdefault(key, []).append((device, fp))
        for key, members in groups.items():
            fps = {fp for _, fp in members}
            if len(fps) > 1:
                bad.append({"array": name, "slice": key,
                            "replicas": members})
    if bad and raise_:
        from deeplearning4j_tpu.faults.errors import SilentCorruptionError
        first = bad[0]
        raise SilentCorruptionError(
            f"cross-replica fingerprint disagreement on "
            f"{first['array']!r}: {first['replicas']} (+{len(bad) - 1} "
            f"more array(s)) — replicas of a DP-sharded parameter must "
            f"match bitwise; one device's copy has silently diverged",
            check="replica_agreement")
    return bad


def check_probes(pairs, starts) -> None:
    """Host-side verdict over a fetched burst of replay-probe pairs:
    ``pairs`` is an (N, 2) uint32 array of (main, replay) digests
    aligned with window-start iterations ``starts``. The first
    disagreement raises with that window's provenance."""
    pairs = np.asarray(pairs)
    if pairs.size == 0:
        return
    for (a, b), start in zip(pairs, starts):
        if int(a) != int(b):
            from deeplearning4j_tpu.faults.errors import \
                SilentCorruptionError
            raise SilentCorruptionError(
                f"replay probe mismatch for the window starting at "
                f"iteration {int(start)}: dispatch fingerprint "
                f"{int(a):#010x} != replay {int(b):#010x} — the same "
                f"program on the same inputs produced different bits "
                f"(SDC or nondeterminism); roll back to the last "
                f"verified checkpoint", check="replay_probe",
                expected=int(b), actual=int(a), step=int(start))


__all__ = ["ALGO", "check_probes", "check_replica_agreement",
           "jnp_leaf_fingerprint", "make_fingerprint_fn",
           "np_fingerprint", "np_leaf_fingerprint",
           "replica_fingerprints", "state_fingerprint",
           "tree_fingerprint", "verify_state_stamp"]
