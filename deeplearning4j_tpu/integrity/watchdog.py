"""Stall watchdog: adaptive deadlines around blocking device boundaries.

The fault rail (faults/) handles failures that RAISE; a wedged
collective, a dead TPU tunnel or a hung host↔device transfer raises
nothing — the process just stops making progress with healthy-looking
/healthz. This module arms a daemon heartbeat thread over every
blocking device boundary the tracer already names:

====================  =====================================================
boundary              guarded call
====================  =====================================================
``window_dispatch``   the fused-window dispatch (autodiff/window.py)
``step_dispatch``     the per-step tier's train dispatch
``flush``             the listener flush's ``jax.device_get`` burst
``serving_execute``   ``ParallelInference._execute``'s graph exec
``checkpoint_capture`` the checkpoint device→host state capture
====================  =====================================================

Each boundary's deadline is ADAPTIVE: ``k ×`` the rolling p50 of its own
recent durations (``monitor.steptime.RollingPercentiles``), floored at
``floor_s``; until ``min_samples`` observations exist — and for any
guard entered with ``first=True`` (a first dispatch that will compile) —
the ``grace_s`` compile grace applies instead, so cold starts and
retraces never false-positive.

On expiry the monitor thread (NOT the wedged one):

1. captures forensics — all-thread stacks (:func:`dump_all_stacks`),
   a live HBM snapshot and the active compiled-program memory plan —
   while the boundary is still wedged;
2. publishes ``{"type": "faults", "event": "stall"}`` (flips
   ``/healthz`` to 503 — monitor/server.py treats ``stall`` as
   degrading) plus a ``{"type": "integrity"}`` forensics record;
3. marks the guard expired. If the blocked call eventually returns
   (a *recoverable* stall), the guard's exit raises a typed
   :class:`~deeplearning4j_tpu.faults.errors.TrainingStalledError`
   carrying the forensics — retryable, so ``FaultTolerantFit`` rolls
   back and retries under its normal budget. A permanent wedge never
   returns, but the record/503/stack dump are already out for the
   supervisor that will kill the process.

When no watchdog is installed, :func:`guard` returns a shared no-op
context — the boundaries pay one global read (bench.py
``integrity_overhead``, ≤2% bar). Clean-path training with the
watchdog armed is bit-identical to unguarded (the guard never touches
the math).
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from deeplearning4j_tpu.monitor.steptime import RollingPercentiles


def dump_all_stacks() -> List[dict]:
    """Snapshot every live thread's Python stack: ``[{name, ident,
    daemon, stack: [frame lines]}, ...]`` — the payload of the
    TelemetryServer's ``GET /stacks`` debug route and of stall
    forensics. Pure introspection; never blocks the dumped threads."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        out.append({
            "name": t.name if t is not None else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [ln.rstrip("\n") for ln in
                      traceback.format_stack(frame)],
        })
    return out


class _NullGuard:
    """Shared no-op context for the uninstalled-watchdog fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullGuard()
_ACTIVE: Optional["StallWatchdog"] = None


def guard(boundary: str, first: bool = False):
    """The boundary seam: a context manager timing this blocking call
    under the installed watchdog (or a shared no-op when none is).
    ``first=True`` marks a call expected to compile — it gets the
    compile grace instead of the adaptive deadline."""
    wd = _ACTIVE
    if wd is None:
        return _NULL
    return wd.guard(boundary, first=first)


def active() -> Optional["StallWatchdog"]:
    return _ACTIVE


class _Guard:
    __slots__ = ("wd", "boundary", "deadline_s", "start", "expired",
                 "error")

    def __init__(self, wd: "StallWatchdog", boundary: str,
                 deadline_s: float):
        self.wd = wd
        self.boundary = boundary
        self.deadline_s = deadline_s
        self.start = 0.0
        self.expired = False
        self.error = None

    def __enter__(self):
        self.start = self.wd._clock()
        self.wd._register(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        waited = self.wd._clock() - self.start
        self.wd._unregister(self, waited)
        if self.expired and self.error is None:
            # the monitor claimed this guard but its forensics dump is
            # still in flight: wait for the typed error briefly so the
            # stall surfaces here, not as a silent 503
            for _ in range(200):
                if self.error is not None:
                    break
                time.sleep(0.01)
        if self.error is not None and exc_type is None:
            # the stall healed (the call returned): surface it typed so
            # the recovery driver can roll back the possibly-suspect
            # boundary instead of training on
            raise self.error
        return False


class StallWatchdog:
    """Daemon heartbeat thread arming adaptive deadlines around
    blocking device boundaries (module docstring).

    ::

        wd = StallWatchdog(storage=storage, k=8.0, floor_s=5.0)
        with wd:                       # install() / uninstall()
            ftf.fit(it, epochs=20)
        wd.stats()                     # {"stalls": ..., "guards": ...}

    ``k``/``floor_s``/``grace_s`` tune the deadline; ``poll_s`` bounds
    detection latency; ``storage`` receives the stall records;
    ``forensics=False`` skips the HBM snapshot (stacks always dump).
    """

    def __init__(self, storage=None, k: float = 8.0, floor_s: float = 5.0,
                 grace_s: float = 120.0, poll_s: float = 0.25,
                 min_samples: int = 3, window: int = 256,
                 forensics: bool = True,
                 clock=time.monotonic):
        self.storage = storage
        self.k = float(k)
        self.floor_s = float(floor_s)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.min_samples = int(min_samples)
        self.forensics = bool(forensics)
        self._clock = clock
        self._window = int(window)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._percentiles: Dict[str, RollingPercentiles] = {}
        self._entries: Dict[int, _Guard] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stalls = 0
        self.guards = 0
        self.events: List[dict] = []

    # -- lifecycle ------------------------------------------------------
    def install(self) -> "StallWatchdog":
        """Become the process-wide watchdog (:func:`guard` routes to
        this instance) and start the monitor thread."""
        global _ACTIVE
        _ACTIVE = self
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="integrity-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- deadlines ------------------------------------------------------
    def deadline_for(self, boundary: str, first: bool = False) -> float:
        """``max(floor, k × rolling-p50)`` — or the compile grace while
        the boundary has fewer than ``min_samples`` observations or the
        caller flagged a first (compiling) dispatch."""
        with self._lock:
            p = self._percentiles.get(boundary)
            n = len(p) if p is not None else 0
            p50 = p.percentile(50) if n else 0.0
        if first or n < self.min_samples:
            return max(self.grace_s, self.floor_s)
        return max(self.floor_s, self.k * p50)

    def guard(self, boundary: str, first: bool = False) -> _Guard:
        return _Guard(self, boundary, self.deadline_for(boundary, first))

    # -- guard bookkeeping ---------------------------------------------
    def _register(self, g: _Guard) -> None:
        with self._cv:
            self.guards += 1
            self._entries[id(g)] = g
            self._cv.notify_all()

    def _unregister(self, g: _Guard, waited: float) -> None:
        with self._cv:
            self._entries.pop(id(g), None)
            p = self._percentiles.get(g.boundary)
            if p is None:
                p = self._percentiles[g.boundary] = \
                    RollingPercentiles(self._window)
            p.add(waited)

    # -- the heartbeat --------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                if not self._entries:
                    self._cv.wait(timeout=self.poll_s)
                    continue
                now = self._clock()
                expired = [g for g in self._entries.values()
                           if not g.expired
                           and now - g.start > g.deadline_s]
                for g in expired:
                    # claimed under the lock BEFORE the (slow) forensics
                    # capture — the next poll cycle must not re-expire
                    # a guard whose dump is still being built
                    g.expired = True
            for g in expired:
                self._expire(g)
            self._stop.wait(self.poll_s)

    def _expire(self, g: _Guard) -> None:
        waited = self._clock() - g.start
        forensics = self._forensics()
        from deeplearning4j_tpu.faults.errors import TrainingStalledError
        g.error = TrainingStalledError(
            f"{g.boundary} stalled: blocked {waited:.3f}s > deadline "
            f"{g.deadline_s:.3f}s (k={self.k} × rolling-p50, floor "
            f"{self.floor_s}s) — forensics (all-thread stacks, HBM "
            f"snapshot, active memory plan) attached; "
            f"{'{'}\"type\": \"faults\", \"event\": \"stall\"{'}'} "
            f"published", boundary=g.boundary, waited_s=round(waited, 6),
            deadline_s=round(g.deadline_s, 6), forensics=forensics)
        self.stalls += 1
        rec = {"type": "faults", "event": "stall", "t": time.time(),
               "boundary": g.boundary, "waited_s": round(waited, 6),
               "deadline_s": round(g.deadline_s, 6),
               "threads": len(forensics.get("stacks", ()))}
        self.events.append(rec)
        if self.storage is not None:
            self.storage.put(rec)
            # the heavyweight forensics ride a separate integrity
            # record so the faults fold stays cheap
            self.storage.put({
                "type": "integrity", "event": "stall_forensics",
                "t": time.time(), "boundary": g.boundary,
                "waited_s": round(waited, 6),
                "stacks": forensics.get("stacks"),
                "active_program": forensics.get("active_program"),
                "hbm": {k: forensics.get("memory", {}).get(k)
                        for k in ("bytes_in_use", "peak_bytes",
                                  "bytes_limit")}})

    def _forensics(self) -> dict:
        out: dict = {"stacks": dump_all_stacks()}
        if not self.forensics:
            return out
        try:
            from deeplearning4j_tpu.monitor import memstats
            out["memory"] = memstats.memory_record(source="watchdog")
            active_plan = memstats.PLANS.active_plan()
            out["active_program"] = active_plan.label \
                if active_plan is not None else None
            if active_plan is not None:
                out["plan"] = active_plan.to_record()
        except Exception as e:      # noqa: BLE001 — forensics must not
            out["memory_error"] = repr(e)     # mask the stall itself
        return out

    def stats(self) -> dict:
        with self._lock:
            per = {b: {"n": len(p), "p50_s": round(p.percentile(50), 6)}
                   for b, p in self._percentiles.items()}
        return {"stalls": self.stalls, "guards": self.guards,
                "boundaries": per}


__all__ = ["StallWatchdog", "active", "dump_all_stacks", "guard"]
