"""AOT dispatch: route executions to ahead-of-time compiled programs.

``jax.jit`` compiles on FIRST CALL — so the first training window and
the first serving request of every shape pay the compiler inline, on
the latency path. JAX's AOT API (``jit_fn.lower(abstract).compile()``)
builds the executable from shapes alone, but the resulting ``Compiled``
object lives outside the jit call cache: a later ``jit_fn(args)`` would
compile AGAIN. :class:`AOTDispatch` closes that gap — it pairs the lazy
jit function with a map of AOT executables keyed by the placeholder
shape signature, dispatching to the prebuilt program when the shapes
match and falling back to lazy jit when they don't (a ragged final
batch nobody predicted still works, it just compiles lazily like
before).

The signature deliberately covers only the *placeholder/stacked-window*
argument: parameter, optimizer-state and constant shapes are fixed for
a given graph version, and the jit cache key that owns this dispatcher
already pins the version — placeholder shapes are the only axis a fit
or serving loop varies. ``Compiled`` itself re-validates every input
aval and raises on mismatch — ``TypeError`` for shape/dtype,
``ValueError`` for sharding — so a stale hit (e.g. resharded inputs
under a mesh) degrades to the lazy path instead of executing the wrong
program.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple


def ph_shape_sig(ph: Dict[str, Any]) -> Tuple:
    """Canonical shape signature of a placeholder dict — the key both
    the window executor's compile accounting and AOT dispatch use, so
    they cannot drift."""
    return tuple(sorted((n, tuple(v.shape)) for n, v in ph.items()))


class AOTDispatch:
    """A jitted train/step function plus its AOT-compiled variants.

    Stored in ``SameDiff._fn_cache`` wherever a bare ``jax.jit`` result
    used to be; callable with the exact same positional signature. With
    no AOT entries (the default) the overhead is one attribute check.
    """

    __slots__ = ("jit_fn", "aot", "ph_arg")

    def __init__(self, jit_fn: Callable, ph_arg: int):
        self.jit_fn = jit_fn
        self.aot: Dict[Tuple, Any] = {}   # shape sig -> jax Compiled
        self.ph_arg = int(ph_arg)         # index of the placeholder dict

    def __call__(self, *args):
        if self.aot:
            compiled = self.aot.get(ph_shape_sig(args[self.ph_arg]))
            if compiled is not None:
                try:
                    return compiled(*args)
                except (TypeError, ValueError):
                    # input aval/sharding mismatch at the executable
                    # boundary (checked BEFORE execution or donation):
                    # fall back to lazy jit, which specializes freely.
                    # jax raises TypeError for aval (shape/dtype)
                    # mismatches but ValueError for sharding mismatches
                    # (mesh-committed inputs against an executable
                    # lowered from unsharded specs)
                    pass
        return self.jit_fn(*args)

    # keep the jit AOT surface reachable (SameDiff.precompile uses it)
    def lower(self, *args, **kwargs):
        return self.jit_fn.lower(*args, **kwargs)


class AOTOutput:
    """An AOT-compiled inference executable paired with its lazy jit
    twin, stored under ``output()``'s exact cache key.

    Unlike :class:`AOTDispatch` (one jit fn, MANY placeholder shapes),
    an output cache key already pins the placeholder signature — there
    is exactly one predicted shape set, so the executable is tried
    first unconditionally. ``Compiled`` re-validates input avals and
    raises on mismatch — ``TypeError`` for a differently-typed PRNG
    key, ``ValueError`` for resharded params — which degrades to the
    lazy jit path instead of executing the wrong program.
    """

    __slots__ = ("jit_fn", "compiled")

    def __init__(self, jit_fn: Callable, compiled: Any):
        self.jit_fn = jit_fn
        self.compiled = compiled

    def __call__(self, params, consts, ph, key):
        try:
            return self.compiled(params, consts, ph, key)
        except (TypeError, ValueError):
            # TypeError = aval mismatch, ValueError = sharding mismatch
            return self.jit_fn(params, consts, ph, key)


__all__ = ["AOTDispatch", "AOTOutput", "ph_shape_sig"]
