"""Persistent compilation cache wiring + process-wide compile accounting.

The reference JVM stack has no analogue: DL4J pays per-op JNI dispatch
and never compiles, so a restarted server is as fast as a warm one.
Under whole-graph XLA compilation the FIRST execution of every distinct
program shape pays seconds of compiler time — a production restart
replays all of it, and a serving process compiles each batch bucket on
the first live request that needs it. JAX ships the fix (a persistent,
content-addressed on-disk executable cache) but it is opt-in and
invisible; this module makes it a wired, observable part of the runtime:

- :func:`configure_cache` applies the cache directory and admission
  knobs to the LIVE process through ``jax.config`` (the
  ``Environment`` property ``compilation_cache_dir`` routes here, so
  ``Environment.set()`` after import actually works — previously the
  property was declared startup-only and a late ``set()`` silently did
  nothing).
- :class:`CompileStats` (singleton :data:`COMPILE_STATS`) counts every
  compile in the process via ``jax.monitoring`` events and splits them
  into persistent-cache HITS (cheap deserialization) vs MISSES (real
  backend compiles), with cumulative wall time per phase. Tests and the
  ``cold_start`` bench assert against deltas of these counters;
  ``MetricsRegistry.fold_compile`` exports them as ``dl4j_compile_*``.
- Each compile phase also lands in the monitor/ tracer ring as a
  synthetic span — ``compile.trace`` (jaxpr tracing), ``compile.lower``
  (StableHLO emission), ``compile.backend`` (XLA compile OR cache
  retrieval, with a ``cache_hit`` arg) — so a Perfetto trace of a cold
  start shows exactly where the seconds went.

What is cacheable: the persistent cache keys on the serialized HLO +
compile options + backend/runtime version, so entries survive process
restarts and machine reboots but NOT jax/jaxlib/libtpu upgrades (the
key changes and the entry is recompiled — stale entries are harmless
disk). Donation, sharding and remat structure are all part of the HLO,
so they cache fine. See docs/cold_start.md.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.monitor.trace import TRACER as _tracer

_STAT_KEYS = ("backend_compiles", "cache_hits", "cache_misses",
              "backend_compile_seconds", "trace_seconds", "lower_seconds",
              "saved_seconds")


class CompileStats:
    """Process-wide XLA compile counters fed by ``jax.monitoring``.

    ``backend_compiles`` counts every compile request that reached the
    backend-compile layer — on a persistent-cache HIT that layer only
    deserializes, so the number of *expensive* compiles is
    ``miss_compiles()`` (= ``backend_compiles - cache_hits``; with the
    cache disabled no hit/miss events fire and every backend compile is
    a real one).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.backend_compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.backend_compile_seconds = 0.0
        self.trace_seconds = 0.0
        self.lower_seconds = 0.0
        self.saved_seconds = 0.0    # compile time the cache saved (jax est.)

    # -- recording (called from jax.monitoring listeners) ---------------
    def _add(self, **fields) -> None:
        with self._lock:
            for k, v in fields.items():
                setattr(self, k, getattr(self, k) + v)

    # -- readout ---------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {k: getattr(self, k) for k in _STAT_KEYS}

    # a mark IS a snapshot; the split exists so call sites read as
    # mark()/delta() bracketing, like Tracer.mark()/drain()
    mark = snapshot

    def delta(self, mark: Dict[str, float]) -> Dict[str, float]:
        """Counters accumulated since ``mark`` (a prior snapshot)."""
        now = self.snapshot()
        out = {k: now[k] - mark.get(k, 0) for k in _STAT_KEYS}
        for k in ("backend_compiles", "cache_hits", "cache_misses"):
            out[k] = int(out[k])
        return out

    def miss_compiles(self) -> int:
        """Expensive (non-cache-hit) compiles so far."""
        with self._lock:
            return max(0, self.backend_compiles - self.cache_hits)

    def to_record(self) -> dict:
        """One ``{"type": "compile"}`` record in the ui/stats JSON-lines
        convention (rendered by ui/report.py, folded by
        ``MetricsRegistry.fold_compile``)."""
        snap = self.snapshot()
        snap["miss_compiles"] = max(0, snap["backend_compiles"]
                                    - snap["cache_hits"])
        return {"type": "compile", "t": time.time(), **snap}

    def publish(self, storage) -> dict:
        rec = self.to_record()
        storage.put(rec)
        return rec


#: The process-wide instance every listener records into.
COMPILE_STATS = CompileStats()

_install_lock = threading.Lock()
_installed = False
_install_failed = False
_tls = threading.local()


def _on_event(event: str, **kw) -> None:
    if event.endswith("/compilation_cache/cache_hits"):
        COMPILE_STATS._add(cache_hits=1)
        # the matching backend_compile duration event (which fires for
        # hits too — it wraps retrieval) marks its span via this flag;
        # compiles are synchronous on the calling thread, so
        # thread-local pairing is race-free
        _tls.pending_hit = True
    elif event.endswith("/compilation_cache/cache_misses"):
        COMPILE_STATS._add(cache_misses=1)
        # a hit whose backend_compile duration event never arrived
        # (aborted compile) must not mislabel THIS compile as a hit
        _tls.pending_hit = False


def _on_duration(event: str, duration: float, **kw) -> None:
    if event.endswith("backend_compile_duration") or \
            event.endswith("backend_compile_time_sec"):
        hit = bool(getattr(_tls, "pending_hit", False))
        _tls.pending_hit = False
        COMPILE_STATS._add(backend_compiles=1,
                           backend_compile_seconds=float(duration))
        _tracer.record_completed("compile.backend", cat="compile",
                                 dur=float(duration), cache_hit=hit)
    elif event.endswith("jaxpr_trace_duration"):
        COMPILE_STATS._add(trace_seconds=float(duration))
        _tracer.record_completed("compile.trace", cat="compile",
                                 dur=float(duration))
    elif event.endswith("jaxpr_to_mlir_module_duration"):
        COMPILE_STATS._add(lower_seconds=float(duration))
        _tracer.record_completed("compile.lower", cat="compile",
                                 dur=float(duration))
    elif event.endswith("compile_time_saved_sec"):
        # jax reports compile_time - retrieval_time; can be negative for
        # programs that compile faster than they deserialize
        COMPILE_STATS._add(saved_seconds=float(duration))


def install_compile_watcher() -> CompileStats:
    """Register the ``jax.monitoring`` listeners feeding
    :data:`COMPILE_STATS` (idempotent; listeners are process-lifetime).
    Called automatically by cache configuration, ``precompile()`` and
    serving warmup — call it directly only to observe purely-lazy
    compilation."""
    global _installed, _install_failed
    with _install_lock:
        if _installed or _install_failed:
            return COMPILE_STATS
        try:
            from jax import monitoring as _mon
            _mon.register_event_listener(_on_event)
            _mon.register_event_duration_secs_listener(_on_duration)
            _installed = True
        except Exception as exc:
            # an all-zero COMPILE_STATS is indistinguishable from a
            # perfectly warm cache downstream (bench warm_cache_hits,
            # ui/report's Compilation section) — warn ONCE instead of
            # silently reporting success-shaped zeros
            _install_failed = True
            import warnings
            warnings.warn(
                f"compile-watcher registration failed ({exc!r}); "
                f"compile accounting is disabled and COMPILE_STATS "
                f"will read zero", stacklevel=2)
    return COMPILE_STATS


def configure_cache(cache_dir: Optional[str],
                    min_entry_size: Optional[int] = None,
                    min_compile_time: Optional[float] = None) -> None:
    """Apply persistent-cache settings to the LIVE jax process.

    ``cache_dir=None``/``""`` disables the cache. ``min_entry_size``
    (bytes; -1 = cache everything) and ``min_compile_time`` (seconds;
    0 = cache everything) gate which executables are worth persisting —
    production defaults skip sub-second compiles, tests set both to the
    cache-everything values. Installs the compile watcher whenever a
    cache is enabled, so hit/miss accounting is always live alongside.
    """
    import jax
    target = cache_dir or None
    dir_changed = jax.config.jax_compilation_cache_dir != target
    jax.config.update("jax_compilation_cache_dir", target)
    if min_entry_size is not None:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(min_entry_size))
    if min_compile_time is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time))
    if dir_changed:
        try:
            # jax initializes its cache object AT MOST ONCE, on the
            # first compile — if anything compiled before this call
            # (importing the framework compiles a few eager helpers),
            # the cache latched "disabled" and the config update above
            # would silently never take effect. Reset to pristine so the
            # next compile re-reads the config — this is what makes a
            # LATE set() actually work. Skipped when the dir is already
            # the live value (the admission knobs are read per-put), so
            # repeated applies — serving warmup calls this once per
            # bucket — don't tear down and re-create the cache backend.
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception as exc:
            # without the reset, a cache object latched "disabled" by a
            # pre-config compile stays disabled — the exact late-set()
            # bug this module exists to fix — so say so instead of
            # silently recompiling everything on every restart
            import warnings
            warnings.warn(
                f"compilation-cache reset failed ({exc!r}); if anything "
                f"compiled before this call the persistent cache may "
                f"remain disabled for this process", stacklevel=2)
    if cache_dir:
        install_compile_watcher()


def cache_dir() -> Optional[str]:
    """The live process's persistent cache directory (None = disabled)."""
    import jax
    return jax.config.jax_compilation_cache_dir


__all__ = ["CompileStats", "COMPILE_STATS", "install_compile_watcher",
           "configure_cache", "cache_dir"]
