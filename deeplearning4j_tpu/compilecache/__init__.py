"""compilecache/ — kill cold-start: persistent XLA compilation cache
wiring, ahead-of-time (AOT) precompilation, and compile observability.

Every other subsystem makes the steady state fast; this one makes the
FIRST step fast. Three rails, all composing with the existing stack:

- :mod:`compilecache.cache` — wires JAX's persistent compilation cache
  (``jax_compilation_cache_dir`` + the min-entry-size / min-compile-time
  admission knobs) into the live process, and keeps process-wide
  :class:`CompileStats` fed by ``jax.monitoring`` events, so every XLA
  compile in the process is counted, timed, and attributed as a
  cache HIT (deserialized from the persistent cache) or MISS (a real
  backend compile). Synthetic ``compile.trace`` / ``compile.lower`` /
  ``compile.backend`` spans land in the monitor/ tracer ring.
- :mod:`compilecache.aot` — the AOT dispatch layer:
  ``SameDiff.precompile()`` and ``ParallelInference(warmup_buckets=...)``
  lower-and-compile programs from *abstract shapes* before the first
  batch/request, and :class:`AOTDispatch` routes matching dispatches to
  the prebuilt executables (falling back to lazy ``jax.jit`` for shapes
  nobody predicted).
- ``bench.py cold_start`` — fresh-process first-compile vs warm-restart
  (populated cache) time per model, so cold-start is a tracked BENCH
  metric next to throughput.

See docs/cold_start.md for the operational story (what is and is not
cacheable across JAX/libtpu versions, cache invalidation, sizing).
"""
from deeplearning4j_tpu.compilecache.aot import (AOTDispatch, AOTOutput,
                                                 ph_shape_sig)
from deeplearning4j_tpu.compilecache.cache import (COMPILE_STATS,
                                                   CompileStats,
                                                   cache_dir,
                                                   configure_cache,
                                                   install_compile_watcher)

__all__ = ["AOTDispatch", "AOTOutput", "ph_shape_sig", "COMPILE_STATS",
           "CompileStats", "cache_dir", "configure_cache",
           "install_compile_watcher"]
