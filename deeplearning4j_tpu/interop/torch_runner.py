"""In-process foreign-model execution with zero-copy tensor exchange.

Reference parity: org.nd4j.tensorflow.conversion.graphrunner.GraphRunner
(GraphRunner.java:52 — load a foreign graph once, keep a persistent
session, feed Map<String, NDArray>, fetch Map<String, NDArray>, with
zero-copy tensor conversion via TensorflowConversion) and
nd4j-onnxruntime's OnnxRuntimeRunner.

TPU-native redesign: the foreign runtime available in this stack is
torch (CPU). TorchRunner keeps a loaded ``torch.nn.Module`` /
TorchScript program as the persistent "session"; conversion crosses the
host boundary zero-copy where the buffer protocols allow it —
numpy → torch via ``torch.from_numpy`` (shared memory), CPU jax arrays
via DLPack, and torch outputs back to numpy via the shared-memory
``.numpy()`` view. TPU-resident jax arrays are device-transferred to
host first (the same D2H the reference pays feeding libnd4j buffers into
TF CPU sessions).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np


def _to_torch(value, torch):
    """Framework array → torch tensor, zero-copy when host-resident."""
    if isinstance(value, torch.Tensor):
        return value
    if isinstance(value, np.ndarray):
        if not value.flags["C_CONTIGUOUS"]:
            value = np.ascontiguousarray(value)
        return torch.from_numpy(value)               # shared memory
    # NDArray (this framework's imperative array)
    data = getattr(value, "data", None)
    if data is not None:
        value = data
    # jax array: DLPack zero-copy on CPU; TPU arrays go through host
    try:
        import jax
        if isinstance(value, jax.Array):
            platform = list(value.devices())[0].platform
            if platform == "cpu":
                try:
                    return torch.from_dlpack(value)
                except Exception:
                    pass
            return torch.from_numpy(np.asarray(value))
    except ImportError:
        pass
    return torch.as_tensor(np.asarray(value))


class TorchRunner:
    """Persistent in-process runner for a torch module (the GraphRunner
    role: construct once, ``run()`` many times).

    model: a ``torch.nn.Module``, a TorchScript file path (``.pt`` saved
    with ``torch.jit.save``), or a callable over torch tensors.
    input_order: feed-dict keys in positional-argument order (defaults
    to sorted feed keys, or the single key for 1-input models).
    output_names: names for the fetched outputs (defaults to
    ``output_0..n``; a dict-returning module uses its own keys).
    """

    def __init__(self, model, input_order: Optional[Sequence[str]] = None,
                 output_names: Optional[Sequence[str]] = None):
        try:
            import torch
        except ImportError as e:                     # pragma: no cover
            raise RuntimeError(
                "TorchRunner needs torch installed (the reference's "
                "GraphRunner equally needs the TF runtime present)") from e
        self._torch = torch
        if isinstance(model, str):
            model = torch.jit.load(model, map_location="cpu")
        if hasattr(model, "eval"):
            model.eval()
        self.model = model
        self.input_order = list(input_order) if input_order else None
        self.output_names = list(output_names) if output_names else None
        self._closed = False

    # -- GraphRunner.run(Map<String,INDArray>) ----------------------------
    def run(self, feed: Dict[str, object]) -> Dict[str, np.ndarray]:
        if self._closed:
            raise RuntimeError("TorchRunner is closed")
        torch = self._torch
        order = self.input_order or (
            list(feed) if len(feed) == 1 else sorted(feed))
        missing = [n for n in order if n not in feed]
        if missing:
            raise KeyError(f"feed missing inputs {missing}; got "
                           f"{sorted(feed)}")
        args = [_to_torch(feed[n], torch) for n in order]
        with torch.no_grad():
            out = self.model(*args)
        return self._name_outputs(out)

    def _name_outputs(self, out) -> Dict[str, np.ndarray]:
        torch = self._torch
        if isinstance(out, dict):
            return {k: v.detach().numpy() for k, v in out.items()}
        if isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        names = self.output_names or [f"output_{i}"
                                      for i in range(len(outs))]
        if len(names) != len(outs):
            raise ValueError(f"model returned {len(outs)} outputs, "
                             f"output_names has {len(names)}")
        res = {}
        for n, t in zip(names, outs):
            res[n] = t.detach().numpy() if isinstance(t, torch.Tensor) \
                else np.asarray(t)
        return res

    def run_to_device(self, feed: Dict[str, object]) -> Dict[str, object]:
        """run() + put outputs on the default JAX device — the fetch-side
        equivalent of the reference's zero-copy back into nd4j."""
        import jax.numpy as jnp
        return {k: jnp.asarray(v) for k, v in self.run(feed).items()}

    # -- lifecycle (GraphRunner implements Closeable) ----------------------
    def close(self) -> None:
        self._closed = True
        self.model = None

    def __enter__(self) -> "TorchRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class OnnxRuntimeRunner:
    """ONNX Runtime in-process runner (reference: nd4j-onnxruntime
    OnnxRuntimeRunner). Same surface as TorchRunner; requires the
    optional onnxruntime package."""

    def __init__(self, model_path: str,
                 output_names: Optional[Sequence[str]] = None):
        try:
            import onnxruntime
        except ImportError as e:
            raise RuntimeError(
                "OnnxRuntimeRunner needs the onnxruntime package, which "
                "is not installed in this environment; import ONNX models "
                "natively with modelimport.onnx_import instead") from e
        self._session = onnxruntime.InferenceSession(model_path)
        self.output_names = list(output_names) if output_names else None

    def run(self, feed: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        feed = {k: np.asarray(v) for k, v in feed.items()}
        names = self.output_names or [o.name
                                      for o in self._session.get_outputs()]
        vals = self._session.run(names, feed)
        return dict(zip(names, vals))
