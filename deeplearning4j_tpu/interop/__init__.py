"""Interop runtimes: run foreign models in-process (reference:
nd4j-tensorflow GraphRunner / nd4j-onnxruntime OnnxRuntimeRunner —
SURVEY.md §2.2 interop row).

The environment ships torch-cpu, so the concrete runner executes
torch/TorchScript modules with zero-copy tensor exchange; the ONNX
Runtime runner has the same surface and activates when onnxruntime is
installed.
"""
from deeplearning4j_tpu.interop.torch_runner import (
    OnnxRuntimeRunner, TorchRunner)

__all__ = ["TorchRunner", "OnnxRuntimeRunner"]
