"""Regularization applied to gradients before the updater.

Reference parity: org.nd4j.linalg.learning.regularization (L1Regularization,
L2Regularization, WeightDecay) as consumed by
deeplearning4j nn/updater/BaseMultiLayerUpdater.update() — L1/L2 modify the
GRADIENT pre-updater; WeightDecay applies to the update post-LR (coeff * w * lr
added to the update when applyLR=true).
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp


class Regularization:
    apply_step: str = "BEFORE_UPDATER"  # or "POST_UPDATER"

    def apply(self, param, grad_or_update, lr):
        raise NotImplementedError

    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(dataclasses.asdict(self))
        return d

    @staticmethod
    def from_json(d: dict) -> "Regularization":
        d = dict(d)
        return _REGS[d.pop("@class")](**d)


@dataclasses.dataclass
class L2Regularization(Regularization):
    """grad += l2 * param (reference: L2Regularization.java)."""
    l2: float = 0.0

    def apply(self, param, grad, lr):
        return grad + self.l2 * param


@dataclasses.dataclass
class L1Regularization(Regularization):
    """grad += l1 * sign(param) (reference: L1Regularization.java)."""
    l1: float = 0.0

    def apply(self, param, grad, lr):
        return grad + self.l1 * jnp.sign(param)


@dataclasses.dataclass
class WeightDecay(Regularization):
    """update += coeff * param [* lr] (reference: WeightDecay.java,
    applied POST_UPDATER so it is not rescaled by adaptive updaters)."""
    coeff: float = 0.0
    apply_lr: bool = True
    apply_step: str = "POST_UPDATER"

    def apply(self, param, update, lr):
        scale = lr if self.apply_lr else 1.0
        return update + self.coeff * scale * param


_REGS: Dict[str, type] = {c.__name__: c for c in
                          [L1Regularization, L2Regularization, WeightDecay]}
