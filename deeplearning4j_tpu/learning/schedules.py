"""Learning-rate (and momentum) schedules.

Reference parity: org.nd4j.linalg.schedule.* (ISchedule + Fixed/Exponential/
Inverse/Map/Poly/Sigmoid/Step/Cycle/Ramp schedules, ScheduleType
ITERATION|EPOCH). Schedules are pure functions of (iteration, epoch) so they
trace into the compiled step — the LR is an XLA scalar input, not a Python
recompile trigger.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp


class ISchedule:
    """value_at(iteration, epoch) → scalar (traceable)."""

    schedule_type: str = "ITERATION"  # or "EPOCH"

    def value_at(self, iteration, epoch):
        raise NotImplementedError

    def _t(self, iteration, epoch):
        return epoch if self.schedule_type == "EPOCH" else iteration

    # serde ------------------------------------------------------------
    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(dataclasses.asdict(self))
        return d

    @staticmethod
    def from_json(d: Optional[dict]) -> Optional["ISchedule"]:
        if d is None:
            return None
        d = dict(d)
        cls_name = d.pop("@class")
        cls = _SCHEDULES[cls_name]
        return cls(**d)


@dataclasses.dataclass
class FixedSchedule(ISchedule):
    value: float = 1e-3

    def value_at(self, iteration, epoch):
        return jnp.asarray(self.value, dtype=jnp.float32)


@dataclasses.dataclass
class ExponentialSchedule(ISchedule):
    """lr = initial * gamma^t (reference: ExponentialSchedule.java)."""
    initial_value: float = 1e-3
    gamma: float = 0.99
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        return self.initial_value * jnp.power(self.gamma, t)


@dataclasses.dataclass
class InverseSchedule(ISchedule):
    """lr = initial / (1 + gamma*t)^power (reference: InverseSchedule.java)."""
    initial_value: float = 1e-3
    gamma: float = 0.1
    power: float = 1.0
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        return self.initial_value / jnp.power(1.0 + self.gamma * t, self.power)


@dataclasses.dataclass
class PolySchedule(ISchedule):
    """lr = initial * (1 - t/maxIter)^power (reference: PolySchedule.java)."""
    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        frac = jnp.clip(t / float(self.max_iter), 0.0, 1.0)
        return self.initial_value * jnp.power(1.0 - frac, self.power)


@dataclasses.dataclass
class SigmoidSchedule(ISchedule):
    """lr = initial / (1 + exp(-gamma*(t - stepSize))) (reference: SigmoidSchedule.java)."""
    initial_value: float = 1e-3
    gamma: float = 0.1
    step_size: int = 100
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        return self.initial_value / (1.0 + jnp.exp(-self.gamma * (t - self.step_size)))


@dataclasses.dataclass
class StepSchedule(ISchedule):
    """lr = initial * decayRate^floor(t/step) (reference: StepSchedule.java)."""
    initial_value: float = 1e-3
    decay_rate: float = 0.5
    step: float = 1000.0
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        return self.initial_value * jnp.power(self.decay_rate, jnp.floor(t / self.step))


@dataclasses.dataclass
class MapSchedule(ISchedule):
    """Piecewise-constant by explicit {t: lr} map (reference: MapSchedule.java —
    requires a value for position 0, rejected at construction otherwise)."""
    values: Dict[int, float] = None
    schedule_type: str = "ITERATION"

    def __post_init__(self):
        if not self.values:
            raise ValueError("MapSchedule requires a values map")
        self.values = {int(k): v for k, v in self.values.items()}
        if 0 not in self.values:
            raise ValueError(
                "MapSchedule values must contain a value for position 0")

    def value_at(self, iteration, epoch):
        t = _f(self._t(iteration, epoch))
        keys = sorted(self.values)
        out = jnp.asarray(self.values[keys[0]], dtype=jnp.float32)
        for k in keys[1:]:
            out = jnp.where(t >= k, self.values[k], out)
        return out


@dataclasses.dataclass
class RampSchedule(ISchedule):
    """Linear warmup wrapper (reference: RampSchedule.java — ramps the
    underlying schedule over numIter iterations)."""
    base: dict = None  # serialized base schedule
    num_iter: int = 1000

    def __post_init__(self):
        if self.base is None:
            raise ValueError("RampSchedule requires a base schedule")
        self._base = ISchedule.from_json(self.base) if isinstance(self.base, dict) else self.base
        if not isinstance(self.base, dict):
            self.base = self._base.to_json()

    def value_at(self, iteration, epoch):
        frac = jnp.clip((_f(iteration) + 1.0) / float(self.num_iter), 0.0, 1.0)
        return frac * self._base.value_at(iteration, epoch)


@dataclasses.dataclass
class CycleSchedule(ISchedule):
    """1-cycle schedule (reference: CycleSchedule.java): linear ramp up over
    stepSize = (cycleLength-annealingLength)/2, linear ramp down, then
    exponential annihilation lr = initial * decay^(annealingLength -
    (cycleLength - pos))."""
    initial_lr: float = 1e-3
    max_lr: float = 1e-2
    cycle_length: int = 1000
    annealing_length: int = 100
    annealing_decay: float = 0.1
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch):
        pos = _f(self._t(iteration, epoch)) % self.cycle_length
        step_size = (self.cycle_length - self.annealing_length) // 2
        increment = (self.max_lr - self.initial_lr) / max(step_size, 1)
        up = self.initial_lr + increment * pos
        down = self.max_lr - increment * (pos - step_size)
        anneal = self.initial_lr * jnp.power(
            self.annealing_decay,
            self.annealing_length - (self.cycle_length - pos))
        return jnp.where(pos < step_size, up,
                         jnp.where(pos < 2 * step_size, down, anneal))


def _f(t):
    return t.astype(jnp.float32) if hasattr(t, "astype") else jnp.asarray(float(t))


_SCHEDULES = {c.__name__: c for c in [
    FixedSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
    SigmoidSchedule, StepSchedule, MapSchedule, RampSchedule, CycleSchedule,
]}


def resolve_lr(lr, iteration, epoch):
    """lr may be a float or an ISchedule."""
    if isinstance(lr, ISchedule):
        return lr.value_at(iteration, epoch)
    return jnp.asarray(lr, dtype=jnp.float32)
