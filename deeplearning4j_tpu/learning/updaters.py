"""Gradient updaters.

Reference parity: org.nd4j.linalg.learning (AdamUpdater, NesterovsUpdater, …)
+ config classes org.nd4j.linalg.learning.config (Sgd/Adam/AdaMax/AMSGrad/
AdaBelief/AdaDelta/AdaGrad/Nadam/Nesterovs/RmsProp/NoOp) and the fused native
updater ops (libnd4j ops/declarable/generic/updaters/). Math follows the
reference updater implementations (e.g. Adam's alphat = lr*sqrt(1-b2^t)/(1-b1^t)
form) so state round-trips are numerically comparable.

Functional design: an updater is (init(params) → state, apply(grads, state,
iteration, epoch) → (updates, new_state)); ``params -= updates``. Everything
is a pytree-of-arrays transform that traces into the ONE compiled training
step — the TPU equivalent of the reference's fused updater kernels.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.learning.schedules import ISchedule, resolve_lr

LrLike = Union[float, ISchedule]


class IUpdater:
    """Base updater (reference: org.nd4j.linalg.learning.config.IUpdater)."""

    def init(self, params):
        """Per-leaf state pytree (tuple of arrays per param leaf)."""
        return jax.tree_util.tree_map(self._leaf_init, params)

    def apply(self, grads, state, iteration, epoch=0):
        """Returns (updates, new_state); caller does params -= updates."""
        lr_t = resolve_lr(getattr(self, "learning_rate", 0.0), iteration, epoch)
        t = jnp.asarray(iteration, dtype=jnp.float32) + 1.0  # 1-based like reference
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_s = treedef.flatten_up_to(state)
        outs = [self._leaf_apply(g, s, lr_t, t) for g, s in zip(flat_g, flat_s)]
        updates = treedef.unflatten([o[0] for o in outs])
        new_state = treedef.unflatten([o[1] for o in outs])
        return updates, new_state

    def _leaf_init(self, p):
        return ()

    def _leaf_apply(self, g, s, lr, t):
        raise NotImplementedError

    # serde ------------------------------------------------------------
    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = v.to_json() if isinstance(v, ISchedule) else v
        return d

    @staticmethod
    def from_json(d: dict) -> "IUpdater":
        d = dict(d)
        cls = UPDATERS[d.pop("@class")]
        kw = {}
        for k, v in d.items():
            if isinstance(v, dict) and "@class" in v:
                v = ISchedule.from_json(v)
            kw[k] = v
        return cls(**kw)

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()

    def __hash__(self):
        return hash((type(self).__name__,
                     tuple(sorted((k, str(v)) for k, v in self.to_json().items()))))


@dataclasses.dataclass(eq=False)
class Sgd(IUpdater):
    """(reference: config/Sgd.java, default lr 1e-3)"""
    learning_rate: LrLike = 1e-3

    def _leaf_apply(self, g, s, lr, t):
        return lr * g, s


@dataclasses.dataclass(eq=False)
class NoOp(IUpdater):
    def _leaf_apply(self, g, s, lr, t):
        return jnp.zeros_like(g), s


@dataclasses.dataclass(eq=False)
class Nesterovs(IUpdater):
    """(reference: config/Nesterovs.java, lr 0.1, momentum 0.9;
    NesterovsUpdater: v' = mu*v - lr*g; update = mu*v - (1+mu)*v')"""
    learning_rate: LrLike = 0.1
    momentum: float = 0.9

    def _leaf_init(self, p):
        return (jnp.zeros_like(p),)

    def _leaf_apply(self, g, s, lr, t):
        (v,) = s
        v_new = self.momentum * v - lr * g
        update = self.momentum * v - (1.0 + self.momentum) * v_new
        return update, (v_new,)


@dataclasses.dataclass(eq=False)
class Adam(IUpdater):
    """(reference: config/Adam.java defaults lr 1e-3, b1 .9, b2 .999, eps 1e-8;
    AdamUpdater: alphat = lr*sqrt(1-b2^t)/(1-b1^t); u = alphat*m/(sqrt(v)+eps))"""
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s, lr, t):
        m, v = s
        m = self.beta1 * m + (1.0 - self.beta1) * g
        v = self.beta2 * v + (1.0 - self.beta2) * g * g
        alphat = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        update = alphat * m / (jnp.sqrt(v) + self.epsilon)
        return update, (m, v)


@dataclasses.dataclass(eq=False)
class AdaMax(IUpdater):
    """(reference: AdaMaxUpdater: u = max(b2*u, |g|); update = lr/(1-b1^t) * m/(u+eps))"""
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s, lr, t):
        m, u = s
        m = self.beta1 * m + (1.0 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        update = (lr / (1.0 - self.beta1 ** t)) * m / (u + self.epsilon)
        return update, (m, u)


@dataclasses.dataclass(eq=False)
class Nadam(IUpdater):
    """(reference: libnd4j nadamUpdater kernel:
    u = lr * (b1*m + (1-b1)*g)/(1-b1^t) / (sqrt(v) + eps) — note v is NOT
    bias-corrected in the reference)"""
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s, lr, t):
        m, v = s
        m = self.beta1 * m + (1.0 - self.beta1) * g
        v = self.beta2 * v + (1.0 - self.beta2) * g * g
        num = (self.beta1 * m + (1.0 - self.beta1) * g) / (1.0 - self.beta1 ** t)
        update = lr * num / (jnp.sqrt(v) + self.epsilon)
        return update, (m, v)


@dataclasses.dataclass(eq=False)
class AMSGrad(IUpdater):
    """(reference: AMSGradUpdater: vH = max(vH, v); u = alphat*m/(sqrt(vH)+eps))"""
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s, lr, t):
        m, v, v_hat = s
        m = self.beta1 * m + (1.0 - self.beta1) * g
        v = self.beta2 * v + (1.0 - self.beta2) * g * g
        v_hat = jnp.maximum(v_hat, v)
        alphat = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        update = alphat * m / (jnp.sqrt(v_hat) + self.epsilon)
        return update, (m, v, v_hat)


@dataclasses.dataclass(eq=False)
class AdaBelief(IUpdater):
    """(reference: AdaBeliefUpdater: s = b2*s + (1-b2)*(g-m)^2 + eps, bias-corrected)"""
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s_, lr, t):
        m, s = s_
        m = self.beta1 * m + (1.0 - self.beta1) * g
        diff = g - m
        s = self.beta2 * s + (1.0 - self.beta2) * diff * diff + self.epsilon
        m_hat = m / (1.0 - self.beta1 ** t)
        s_hat = s / (1.0 - self.beta2 ** t)
        update = lr * m_hat / (jnp.sqrt(s_hat) + self.epsilon)
        return update, (m, s)


@dataclasses.dataclass(eq=False)
class AdaDelta(IUpdater):
    """(reference: config/AdaDelta.java rho .95, eps 1e-6; no learning rate)"""
    rho: float = 0.95
    epsilon: float = 1e-6

    def _leaf_init(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def _leaf_apply(self, g, s, lr, t):
        msg, msdx = s
        msg = self.rho * msg + (1.0 - self.rho) * g * g
        update = g * jnp.sqrt(msdx + self.epsilon) / jnp.sqrt(msg + self.epsilon)
        msdx = self.rho * msdx + (1.0 - self.rho) * update * update
        return update, (msg, msdx)


@dataclasses.dataclass(eq=False)
class AdaGrad(IUpdater):
    """(reference: config/AdaGrad.java lr 1e-1, eps 1e-6)"""
    learning_rate: LrLike = 1e-1
    epsilon: float = 1e-6

    def _leaf_init(self, p):
        return (jnp.zeros_like(p),)

    def _leaf_apply(self, g, s, lr, t):
        (h,) = s
        h = h + g * g
        update = lr * g / (jnp.sqrt(h) + self.epsilon)
        return update, (h,)


@dataclasses.dataclass(eq=False)
class RmsProp(IUpdater):
    """(reference: config/RmsProp.java lr 1e-1, rmsDecay .95, eps 1e-8)"""
    learning_rate: LrLike = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def _leaf_init(self, p):
        return (jnp.zeros_like(p),)

    def _leaf_apply(self, g, s, lr, t):
        (r,) = s
        r = self.rms_decay * r + (1.0 - self.rms_decay) * g * g
        update = lr * g / (jnp.sqrt(r) + self.epsilon)
        return update, (r,)


UPDATERS: Dict[str, type] = {c.__name__: c for c in [
    Sgd, NoOp, Nesterovs, Adam, AdaMax, Nadam, AMSGrad, AdaBelief, AdaDelta,
    AdaGrad, RmsProp,
]}
