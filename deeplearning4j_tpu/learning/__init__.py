from deeplearning4j_tpu.learning.schedules import (
    CycleSchedule, ExponentialSchedule, FixedSchedule, ISchedule,
    InverseSchedule, MapSchedule, PolySchedule, RampSchedule, SigmoidSchedule,
    StepSchedule, resolve_lr,
)
from deeplearning4j_tpu.learning.updaters import (
    UPDATERS, AMSGrad, AdaBelief, AdaDelta, AdaGrad, AdaMax, Adam, IUpdater,
    Nadam, Nesterovs, NoOp, RmsProp, Sgd,
)
from deeplearning4j_tpu.learning.regularization import (
    L1Regularization, L2Regularization, Regularization, WeightDecay,
)

__all__ = [
    "ISchedule", "FixedSchedule", "ExponentialSchedule", "InverseSchedule",
    "PolySchedule", "SigmoidSchedule", "StepSchedule", "MapSchedule",
    "RampSchedule", "CycleSchedule", "resolve_lr",
    "IUpdater", "Sgd", "NoOp", "Nesterovs", "Adam", "AdaMax", "Nadam",
    "AMSGrad", "AdaBelief", "AdaDelta", "AdaGrad", "RmsProp", "UPDATERS",
    "Regularization", "L1Regularization", "L2Regularization", "WeightDecay",
]
